"""Search strategies for the tuning session.

Two interchangeable strategies over the same knob space:

* ``CoordinateSearch`` ("grid") — deterministic coordinate descent:
  knobs are swept in declaration order, one candidate per sampling
  window, and after each sweep the best-scoring candidate (ties break
  toward the default) is locked in before the next knob starts.  No
  randomness anywhere — the strategy the tests and chaos drills pin.
* ``GPSearch`` ("gp") — the same coordinate loop, but continuous
  knobs are sampled by the resurrected Gaussian-process Expected-
  Improvement sampler (common/optim/bayesian_optimization.py, the
  reference parameter_manager lineage) under a fixed seed, so a given
  (seed, score stream) replays to the same proposals.

A knob space is an ordered ``{name: KnobSpec}``; continuous specs
carry (lo, hi) bounds + a sample budget, categorical specs a candidate
tuple.  Both strategies expose the same surface::

    s.current       # the full knob vector to run NEXT window
    s.advance(score)  # score the window just finished -> bool changed
    s.converged     # search space exhausted
    s.best, s.best_score, s.samples
"""

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["KnobSpec", "CoordinateSearch", "GPSearch", "make_strategy"]


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One searchable knob: ``candidates`` for categorical/grid
    dimensions, or ``bounds`` (+ ``gp_samples``) for continuous ones —
    a continuous spec still carries candidates as the grid-strategy
    fallback."""
    default: object
    candidates: Tuple = ()
    bounds: Optional[Tuple[float, float]] = None
    gp_samples: int = 8

    def grid(self) -> Tuple:
        cands = tuple(self.candidates)
        if self.default in cands:
            # Default first: ties adopt the stock configuration, so a
            # flat objective can never "tune" away from the default.
            cands = (self.default,) + tuple(
                c for c in cands if c != self.default)
        else:
            cands = (self.default,) + cands
        return cands


class CoordinateSearch:
    def __init__(self, space: Dict[str, KnobSpec]):
        self._space = dict(space)
        self._order = list(space)
        self._vector = {k: s.default for k, s in space.items()}
        self._ki = 0
        self._ci = 0
        self._scores = []        # scores for the knob being swept
        self._cands = self._grid_for(0)
        self.samples = 0
        self.converged = not self._order
        self.best_score: Optional[float] = None

    def _grid_for(self, ki: int):
        if ki >= len(self._order):
            return ()
        return self._space[self._order[ki]].grid()

    @property
    def current(self) -> dict:
        v = dict(self._vector)
        if not self.converged:
            v[self._order[self._ki]] = self._cands[self._ci]
        return v

    @property
    def best(self) -> dict:
        return dict(self._vector)

    def advance(self, score: float) -> bool:
        """Record ``score`` for the vector in ``current`` and move to
        the next proposal.  Returns True when ``current`` changed."""
        if self.converged:
            return False
        self.samples += 1
        self._scores.append(float(score))
        prev = self.current
        self._ci += 1
        if self._ci >= len(self._cands):
            # Adopt the best candidate for this knob; max() keeps the
            # FIRST maximum, and the grid puts the default first, so a
            # tie adopts the default.
            knob = self._order[self._ki]
            best_i = max(range(len(self._scores)),
                         key=lambda i: self._scores[i])
            self._vector[knob] = self._cands[best_i]
            self.best_score = self._scores[best_i]
            self._scores = []
            self._ki += 1
            self._ci = 0
            if self._ki >= len(self._order):
                self.converged = True
            else:
                self._cands = self._grid_for(self._ki)
        return self.current != prev or self.converged

    def finish(self):
        """Force convergence (sample budget exhausted): adopt the best
        candidate seen so far for the knob mid-sweep, keep defaults
        for knobs never reached.  Deterministic like advance()."""
        if self.converged:
            return
        if self._scores:
            knob = self._order[self._ki]
            best_i = max(range(len(self._scores)),
                         key=lambda i: self._scores[i])
            self._vector[knob] = self._cands[best_i]
            self.best_score = self._scores[best_i]
            self._scores = []
        self.converged = True

    def adopt(self, vector: dict, score: float = None):
        """Pre-freeze the search on an externally chosen vector (a
        reloaded tuned profile): known knobs are adopted, the search
        is marked converged, nothing is ever proposed."""
        self._vector.update(
            {k: v for k, v in vector.items() if k in self._vector})
        if score is not None:
            self.best_score = float(score)
        self._scores = []
        self.converged = True


class GPSearch(CoordinateSearch):
    """Coordinate descent where continuous knobs (those declaring
    ``bounds``) are sampled by GP Expected Improvement instead of the
    fixed grid.  Deterministic under a fixed seed: the only randomness
    is the seeded proposal RNG inside BayesianOptimization."""

    def __init__(self, space: Dict[str, KnobSpec], seed: int = 0,
                 gp_noise: float = 0.8):
        self._seed = seed
        self._gp_noise = gp_noise
        self._bo = None
        self._bo_x = None
        self._bo_budget = 0
        super().__init__(space)

    def _spec(self, ki: int) -> Optional[KnobSpec]:
        if ki >= len(self._order):
            return None
        return self._space[self._order[ki]]

    def _grid_for(self, ki: int):
        spec = self._spec(ki)
        if spec is not None and spec.bounds is not None:
            from ..common.optim import BayesianOptimization
            self._bo = BayesianOptimization(
                bounds=[spec.bounds], gp_noise=self._gp_noise,
                seed=self._seed + ki)
            self._bo_budget = max(2, int(spec.gp_samples))
            self._bo_x = [float(spec.default)]
            # One pseudo-candidate slot per budgeted sample; current()
            # reads the actual value from _bo_x.
            return ("gp",) * self._bo_budget
        self._bo = None
        return super()._grid_for(ki)

    @property
    def current(self) -> dict:
        v = dict(self._vector)
        if not self.converged:
            knob = self._order[self._ki]
            if self._bo is not None:
                v[knob] = round(float(self._bo_x[0]), 4)
            else:
                v[knob] = self._cands[self._ci]
        return v

    def advance(self, score: float) -> bool:
        if self.converged or self._bo is None:
            return super().advance(score)
        self.samples += 1
        prev = self.current
        knob = self._order[self._ki]
        self._bo.add_sample([float(self._bo_x[0])], float(score))
        self._ci += 1
        if self._ci >= self._bo_budget:
            best = self._bo.best
            spec = self._space[knob]
            if best is not None:
                self._vector[knob] = round(float(best[0][0]), 4)
                self.best_score = float(best[1])
            else:
                self._vector[knob] = spec.default
            self._ci = 0
            self._ki += 1
            if self._ki >= len(self._order):
                self.converged = True
            else:
                self._cands = self._grid_for(self._ki)
        else:
            self._bo_x = [float(self._bo.next_sample()[0])]
        return self.current != prev or self.converged

    def finish(self):
        if self.converged:
            return
        if self._bo is not None and self._bo.best is not None:
            knob = self._order[self._ki]
            self._vector[knob] = round(float(self._bo.best[0][0]), 4)
            self.best_score = float(self._bo.best[1])
            self.converged = True
            return
        super().finish()


def make_strategy(name: str, space: Dict[str, KnobSpec],
                  seed: int = 0, gp_noise: float = 0.8):
    if name == "gp":
        return GPSearch(space, seed=seed, gp_noise=gp_noise)
    if name == "grid":
        return CoordinateSearch(space)
    raise ValueError("unknown tune strategy %r (grid|gp)" % (name,))
