"""Shared Keras implementation used by ``horovod_tpu.keras`` and
``horovod_tpu.tensorflow.keras`` (reference: horovod/_keras/__init__.py
— create_distributed_optimizer via dynamic subclassing, broadcast
helpers).

Built against Keras 3 (``tf.keras`` is Keras 3 in TF ≥ 2.16): the
override point is ``BaseOptimizer.apply`` — both the TF trainer's
``apply_gradients`` and the JAX trainer's ``stateless_apply`` funnel
through it.  On the TF backend, gradients reduce via the in-graph /
py_function TF plane; on the JAX backend they reduce from INSIDE
keras's jit-compiled train step via ``io_callback`` into the fused
collective data plane (on TPU: XLA collectives over ICI), so model
compute never leaves the chip.
"""

from typing import List, Optional

import numpy as np

from ..common import basics
from ..common.basics import Average, Sum, global_process_set
from .. import ops as _ops
from ..ops.compression import Compression


def _scales(op, gradient_predivide_factor, process_set):
    # Resolved at CALL time, never frozen at optimizer creation:
    # process_set.size() changes across elastic resets (same
    # convention as tensorflow/__init__.py _make_allreduce_grads_fn).
    if op == Average:
        return (1.0 / gradient_predivide_factor,
                gradient_predivide_factor / process_set.size(), Sum)
    return 1.0, 1.0, op


def _active_distribution_scope():
    """Classify the active keras distribution for gradient-sync
    purposes.  Returns one of:

    * ``"global"`` — a distribution whose device mesh spans EVERY jax
      process: the jit-compiled train step is one SPMD program over
      the whole job and XLA already inserted the gradient all-reduce
      (ICI/DCN) during partitioning.  Gradient sync is the identity;
      gradients never leave the accelerators (the property of the
      reference's NCCL path, nccl_operations.cc:126-184, achieved by
      fusing the collective INTO the step).
    * ``"local"`` — a distribution over this process's devices only
      while the world has size > 1: the step is multi-device (ordered
      io_callback cannot lower) but replicas on OTHER processes see
      none of it — unsupported; the caller raises with guidance.
    * ``None`` — no distribution: keras jits on one local device and
      the io_callback eager plane applies.
    """
    try:
        from keras import distribution as kd
        dist = kd.distribution()
    except Exception:
        return None
    if dist is None:
        return None
    try:
        devs = list(dist.device_mesh.devices.flatten())
    except Exception:
        return None
    import jax
    procs = {getattr(d, "process_index", 0) for d in devs}
    if len(procs) >= jax.process_count():
        return "global"
    return "local"


def _jax_grads_fn(compression, op, gradient_predivide_factor,
                  process_set):
    """Gradient reduction for the Keras-3 JAX backend.

    Two planes, chosen per call (so ``hvd.keras.set_data_parallel``
    may run before or after optimizer creation):

    * **In-graph (preferred on TPU)** — with a keras distribution
      spanning the whole job (``set_data_parallel``), gradients are
      reduced by XLA-inserted collectives inside the compiled SPMD
      step; this function is the identity there.
    * **Eager io_callback** — without a distribution, keras's JAX
      trainer jit-compiles the train step on ONE local device and
      calls ``optimizer.stateless_apply`` inside the traced program;
      ``jax.experimental.io_callback`` suspends the compiled step,
      runs the grouped allreduce on the eager data plane (on TPU the
      fused XLA collective over ICI — the same structure as the
      reference's GPU-compute + NCCL-enqueue split,
      tensorflow/mpi_ops.cc:374-428), and resumes on-chip.
      ``ordered=True`` keeps the per-rank submission order identical,
      which the coordinator's fusion relies on."""
    import jax
    from jax.experimental import io_callback

    def host_reduce(gate, *arrs):
        # Runs EAGERLY once per step (the compiled program suspends
        # into it), so world size and scale factors track elastic
        # resizes even though the traced program is cached.  A zero
        # gate (non-update step under gradient accumulation) skips the
        # wire entirely; every rank computes the same gate so the
        # coordinator's submission counts stay in lockstep.
        if not int(gate):
            return tuple(np.ascontiguousarray(np.asarray(a))
                         for a in arrs)
        prescale, postscale, reduce_op = _scales(
            op, gradient_predivide_factor, process_set)
        arrs = [np.asarray(a) for a in arrs]
        compressed, ctxs = [], []
        for a in arrs:
            c, ctx = compression.compress(a)
            compressed.append(c)
            ctxs.append(ctx)
        reduced = _ops.grouped_allreduce(
            compressed, op=reduce_op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=process_set)
        return tuple(
            np.ascontiguousarray(compression.decompress(r, ctx))
            for r, ctx in zip(reduced, ctxs))

    warned_idle = []

    def allreduce_grads(grads, variables=None, gate=None):
        grads = list(grads)
        index = [i for i, g in enumerate(grads) if g is not None]
        # The skip may be decided at TRACE time only when the world
        # can never grow (non-elastic): the callback must be baked
        # into the cached program whenever a resize could make it
        # necessary later.
        static_single = (process_set.size() == 1 and
                         not basics._state().knobs.elastic)
        if not index or static_single:
            # Size-1 non-elastic worlds sync nothing, whatever knobs
            # or local keras distribution are in play — keep the
            # pre-round-5 behavior where eager-plane knobs are
            # harmless no-ops there.
            return grads
        scope = _active_distribution_scope()
        if scope == "global":
            # One SPMD program over every chip in the job: XLA already
            # reduced the gradients in-graph.  Knobs that only make
            # sense on the eager wire cannot apply here.
            if compression is not Compression.none or \
                    gradient_predivide_factor != 1.0 or op != Average:
                # The SPMD program computes the global-batch MEAN
                # gradient (= Average); Sum/compression/predivide are
                # eager-wire semantics with no in-graph counterpart.
                raise ValueError(
                    "compression / gradient_predivide_factor / "
                    "op=%r are eager-plane options and do not apply "
                    "to the in-graph data-parallel plane installed by "
                    "hvd.keras.set_data_parallel(); remove them or "
                    "drop the keras distribution." % (op,))
            if process_set is not global_process_set:
                raise ValueError(
                    "process_set sub-worlds are not supported with "
                    "the in-graph keras distribution (the SPMD "
                    "program spans the whole job)")
            return grads
        if scope == "local":
            raise NotImplementedError(
                "A keras distribution over this process's local "
                "devices only cannot be combined with size > 1: the "
                "multi-device train step cannot suspend into the "
                "eager collective plane (ordered io_callback), and "
                "other ranks' replicas would desync.  Use "
                "hvd.keras.set_data_parallel() AFTER hvd.init() to "
                "span the whole job in-graph instead.")
        if jax.local_device_count() > 1 and not warned_idle:
            warned_idle.append(True)
            import warnings
            warnings.warn(
                "hvd.DistributedOptimizer (Keras JAX backend): this "
                f"process sees {jax.local_device_count()} devices but "
                "keras compiles on one; the rest idle. Call "
                "hvd.keras.set_data_parallel() after hvd.init() to "
                "train one in-graph SPMD program over every chip.",
                stacklevel=3)
        flat = [grads[i] for i in index]
        shapes = tuple(jax.ShapeDtypeStruct(g.shape, g.dtype)
                       for g in flat)
        # The gate rides as a traced operand: with gradient
        # accumulation the callback must run EVERY step (static
        # program, coordinator submission order), but the wire
        # collective is skipped on non-update steps — all ranks
        # compute the same gate (iterations advance in lockstep), so
        # the coordinator's counts stay aligned.
        import jax.numpy as jnp
        gate_t = jnp.asarray(1, jnp.int32) if gate is None else \
            jnp.asarray(gate, jnp.int32)
        reduced = io_callback(host_reduce, shapes, gate_t, *flat,
                              ordered=True)
        if not isinstance(reduced, (list, tuple)):
            reduced = (reduced,)
        for i, r in zip(index, reduced):
            grads[i] = r
        return grads

    allreduce_grads.supports_gate = True
    return allreduce_grads


def _backend_grads_fn(compression, op, gradient_predivide_factor,
                      process_set):
    """Backend-neutral (eager) gradient reduction via keras.ops
    conversion — the fallback for backends without a dedicated path."""
    from keras import ops as K
    from .. import ops as _ops

    def allreduce_grads(grads, variables=None):
        prescale, postscale, reduce_op = _scales(
            op, gradient_predivide_factor, process_set)
        index = [i for i, g in enumerate(grads) if g is not None]
        arrs = [np.asarray(K.convert_to_numpy(grads[i])) for i in index]
        compressed, ctxs = [], []
        for a in arrs:
            c, ctx = compression.compress(a)
            compressed.append(c)
            ctxs.append(ctx)
        reduced = _ops.grouped_allreduce(
            compressed, op=reduce_op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=process_set) \
            if compressed else []
        out = list(grads)
        for i, r, ctx in zip(index, reduced, ctxs):
            out[i] = K.convert_to_tensor(
                np.asarray(compression.decompress(r, ctx)))
        return out

    return allreduce_grads


def create_distributed_optimizer(optimizer, name=None,
                                 compression=Compression.none,
                                 sparse_as_dense=False,
                                 backward_passes_per_step=1, op=Average,
                                 gradient_predivide_factor=1.0,
                                 average_aggregated_gradients=False,
                                 num_groups=None,
                                 process_set=global_process_set,
                                 make_allreduce_grads_fn=None):
    if make_allreduce_grads_fn is None:
        # Pick by the ACTIVE Keras backend, not TF importability: with
        # KERAS_BACKEND=jax the trainer feeds JAX arrays (often
        # tracers), which must not route through tf.py_function.
        import keras
        if keras.backend.backend() == "tensorflow":
            try:
                from ..tensorflow import _make_allreduce_grads_fn as _fn
                make_allreduce_grads_fn = _fn
            except ImportError:
                make_allreduce_grads_fn = None
    if make_allreduce_grads_fn is not None:
        allreduce_grads = make_allreduce_grads_fn(
            name or "DistributedOptimizer", "", "", compression,
            sparse_as_dense, op, gradient_predivide_factor, num_groups,
            process_set)
    else:
        import keras
        if keras.backend.backend() == "jax":
            allreduce_grads = _jax_grads_fn(
                compression, op, gradient_predivide_factor,
                process_set)
        else:
            allreduce_grads = _backend_grads_fn(
                compression, op, gradient_predivide_factor,
                process_set)

    cls = optimizer.__class__

    class _DistributedOptimizer(cls):
        _hvd_distributed = True

        # The hook point is ``apply``: EVERY path funnels through it —
        # eager/TF ``apply_gradients`` delegates to it, and the JAX
        # trainer's jitted train step calls ``stateless_apply``, which
        # invokes ``apply`` directly (so an apply_gradients-only
        # override would silently skip gradient sync under
        # KERAS_BACKEND=jax model.fit).
        def apply(self, grads, trainable_variables=None):
            if self._hvd_backward_passes > 1:
                # Accumulation mode: the sync moves to
                # _backend_update_step (below), which keras hands the
                # AVERAGED ACCUMULATED gradients exactly on update
                # steps — compiled or eager, any backend (reference
                # semantics: tensorflow/gradient_aggregation.py's
                # LocalGradientAggregationHelper, re-expressed on
                # keras-3's native gradient_accumulation_steps).
                return super().apply(grads, trainable_variables)
            reduced = self._hvd_allreduce_grads(
                list(grads), trainable_variables)
            return super().apply(reduced, trainable_variables)

        def _clip_gradients(self, grads):
            if self._hvd_backward_passes > 1:
                # Deferred to _backend_update_step so clipnorm/
                # clipvalue apply to the SYNCED gradient (clip of the
                # average, at the user's threshold) — same ordering as
                # the backward_passes=1 path, where apply() reduces
                # before super().apply clips.
                return grads
            return super()._clip_gradients(grads)

        def _backend_update_step(self, grads, trainable_variables,
                                 learning_rate):
            if self._hvd_backward_passes > 1:
                from keras import ops as K
                n = self._hvd_backward_passes
                # Mirrors keras's is_update_step: on the jax backend
                # this method runs EVERY step (with discarded results
                # off-step); the gate lets the reducer skip the wire
                # on non-update steps while keeping the per-step
                # callback order identical on all ranks.
                gate = K.equal(K.mod(self._iterations + 1, n), 0)
                if not self._hvd_average_aggregated:
                    # keras accumulates the MEAN over the N passes;
                    # the reference default is their SUM (then the
                    # reducer averages across ranks).
                    grads = [g * float(n) if g is not None else None
                             for g in grads]
                fn = self._hvd_allreduce_grads
                if getattr(fn, "supports_gate", False):
                    grads = fn(grads, trainable_variables, gate=gate)
                else:
                    # Reducing off-step values is numerically safe:
                    # keras discards every off-step update (cond /
                    # value-select), and all ranks reduce in lockstep.
                    grads = fn(grads, trainable_variables)
                grads = super()._clip_gradients(list(grads))
            super()._backend_update_step(grads, trainable_variables,
                                         learning_rate)

    dist_name = name or "Distributed" + cls.__name__
    _DistributedOptimizer.__name__ = dist_name
    config = optimizer.get_config()
    if backward_passes_per_step > 1:
        # Local accumulation rides keras-3's native machinery (state
        # in optimizer slots, cond/value-select per backend) so it
        # works inside compiled train steps.
        if config.get("gradient_accumulation_steps"):
            raise ValueError(
                "Pass either backward_passes_per_step (horovod API) "
                "or gradient_accumulation_steps (keras API), not "
                "both.")
        config["gradient_accumulation_steps"] = backward_passes_per_step
    new_opt = _DistributedOptimizer.from_config(config)
    new_opt._hvd_allreduce_grads = allreduce_grads
    new_opt._hvd_backward_passes = backward_passes_per_step
    new_opt._hvd_average_aggregated = average_aggregated_gradients
    # Carry over any state the optimizer had (slot variables are
    # created lazily, so a freshly-configured clone is equivalent).
    return new_opt


def broadcast_variables(variables, root_rank: int,
                        process_set=global_process_set):
    for i, var in enumerate(variables):
        name = getattr(var, "name", None) or f"bcast_var.{i}"
        value = _ops.broadcast(np.asarray(var), root_rank,
                               name=f"kbcast/{name}.{i}",
                               process_set=process_set)
        var.assign(np.asarray(value))


def broadcast_model(model, root_rank: int,
                    process_set=global_process_set):
    weights = model.get_weights()
    out = []
    for i, w in enumerate(weights):
        out.append(np.asarray(_ops.broadcast(
            w, root_rank, name=f"kbcast_model/{i}",
            process_set=process_set)))
    model.set_weights(out)
