"""Shared Keras implementation used by ``horovod_tpu.keras`` and
``horovod_tpu.tensorflow.keras`` (reference: horovod/_keras/__init__.py
— create_distributed_optimizer via dynamic subclassing, broadcast
helpers).

Built against Keras 3 (``tf.keras`` is Keras 3 in TF ≥ 2.16): the
override point is ``apply_gradients``, which every backend's train step
calls.  Gradients stage through host memory into the background
runtime, matching the TF binding's design.
"""

from typing import List, Optional

import numpy as np

from ..common import basics
from ..common.basics import Average, Sum, global_process_set
from .. import ops as _ops
from ..ops.compression import Compression


def _backend_grads_fn(compression, op, gradient_predivide_factor,
                      process_set):
    """Backend-neutral gradient reduction via keras.ops conversion —
    used when TensorFlow is not installed (Keras on the JAX backend)."""
    from keras import ops as K
    from .. import ops as _ops

    def allreduce_grads(grads, variables=None):
        if op == Average:
            prescale = 1.0 / gradient_predivide_factor
            postscale = gradient_predivide_factor / process_set.size()
            reduce_op = Sum
        else:
            prescale, postscale, reduce_op = 1.0, 1.0, op
        index = [i for i, g in enumerate(grads) if g is not None]
        arrs = [np.asarray(K.convert_to_numpy(grads[i])) for i in index]
        compressed, ctxs = [], []
        for a in arrs:
            c, ctx = compression.compress(a)
            compressed.append(c)
            ctxs.append(ctx)
        reduced = _ops.grouped_allreduce(
            compressed, op=reduce_op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=process_set) \
            if compressed else []
        out = list(grads)
        for i, r, ctx in zip(index, reduced, ctxs):
            out[i] = K.convert_to_tensor(
                np.asarray(compression.decompress(r, ctx)))
        return out

    return allreduce_grads


def create_distributed_optimizer(optimizer, name=None,
                                 compression=Compression.none,
                                 sparse_as_dense=False,
                                 backward_passes_per_step=1, op=Average,
                                 gradient_predivide_factor=1.0,
                                 average_aggregated_gradients=False,
                                 num_groups=None,
                                 process_set=global_process_set,
                                 make_allreduce_grads_fn=None):
    if make_allreduce_grads_fn is None:
        # Pick by the ACTIVE Keras backend, not TF importability: with
        # KERAS_BACKEND=jax the trainer feeds JAX arrays, which must
        # not route through tf.py_function.
        import keras
        if keras.backend.backend() == "tensorflow":
            try:
                from ..tensorflow import _make_allreduce_grads_fn as _fn
                make_allreduce_grads_fn = _fn
            except ImportError:
                make_allreduce_grads_fn = None
    if make_allreduce_grads_fn is not None:
        allreduce_grads = make_allreduce_grads_fn(
            name or "DistributedOptimizer", "", "", compression,
            sparse_as_dense, op, gradient_predivide_factor, num_groups,
            process_set)
    else:
        allreduce_grads = _backend_grads_fn(
            compression, op, gradient_predivide_factor, process_set)

    cls = optimizer.__class__

    class _DistributedOptimizer(cls):
        _hvd_distributed = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            try:
                import tensorflow as tf
                eager = tf.executing_eagerly()
            except ImportError:
                eager = True
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            variables = [v for _, v in grads_and_vars]
            if self._hvd_backward_passes > 1:
                if not eager:
                    raise NotImplementedError(
                        "backward_passes_per_step > 1 requires eager "
                        "execution (compile with run_eagerly=True); the "
                        "compiled-path equivalent lives in "
                        "horovod_tpu.jax / horovod_tpu.training.")
                grads = self._hvd_accumulate(grads)
                if grads is None:
                    return None
            reduced = self._hvd_allreduce_grads(grads, variables)
            return super().apply_gradients(
                zip(reduced, variables), *args, **kwargs)

        def _hvd_accumulate(self, grads):
            acc = self.__dict__.setdefault("_hvd_acc", None)
            n = self.__dict__.setdefault("_hvd_count", 0) + 1
            if acc is None:
                acc = [np.array(g) for g in grads]
            else:
                acc = [a + np.array(g) for a, g in zip(acc, grads)]
            if n < self._hvd_backward_passes:
                self.__dict__["_hvd_acc"] = acc
                self.__dict__["_hvd_count"] = n
                return None
            self.__dict__["_hvd_acc"] = None
            self.__dict__["_hvd_count"] = 0
            scale = (self._hvd_backward_passes
                     if self._hvd_average_aggregated else 1)
            return [a / scale for a in acc]

    dist_name = name or "Distributed" + cls.__name__
    _DistributedOptimizer.__name__ = dist_name
    new_opt = _DistributedOptimizer.from_config(optimizer.get_config())
    new_opt._hvd_allreduce_grads = allreduce_grads
    new_opt._hvd_backward_passes = backward_passes_per_step
    new_opt._hvd_average_aggregated = average_aggregated_gradients
    # Carry over any state the optimizer had (slot variables are
    # created lazily, so a freshly-configured clone is equivalent).
    return new_opt


def broadcast_variables(variables, root_rank: int,
                        process_set=global_process_set):
    for i, var in enumerate(variables):
        name = getattr(var, "name", None) or f"bcast_var.{i}"
        value = _ops.broadcast(np.asarray(var), root_rank,
                               name=f"kbcast/{name}.{i}",
                               process_set=process_set)
        var.assign(np.asarray(value))


def broadcast_model(model, root_rank: int,
                    process_set=global_process_set):
    weights = model.get_weights()
    out = []
    for i, w in enumerate(weights):
        out.append(np.asarray(_ops.broadcast(
            w, root_rank, name=f"kbcast_model/{i}",
            process_set=process_set)))
    model.set_weights(out)
