"""Shared Keras callback implementations (reference:
horovod/_keras/callbacks.py, re-exported by keras/callbacks.py:22-160).
"""

import warnings

import numpy as np

from ..common import basics
from ..common.basics import Average, global_process_set
from .. import ops as _ops
from . import broadcast_model, broadcast_variables

import keras


class BroadcastGlobalVariablesCallbackImpl:
    def __init__(self, backend, root_rank, device="", *args):
        super().__init__(*args)
        self.backend = backend
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        broadcast_model(self.model, self.root_rank)
        if hasattr(self.model, "optimizer") and \
                self.model.optimizer is not None:
            opt_vars = getattr(self.model.optimizer, "variables", None)
            if callable(opt_vars):
                opt_vars = opt_vars()
            if opt_vars:
                broadcast_variables(opt_vars, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallbackImpl:
    def __init__(self, backend, *args):
        super().__init__(*args)
        self.backend = backend

    def _average_metrics_in_place(self, logs):
        logs = logs or {}
        for metric, value in list(logs.items()):
            if isinstance(value, (int, float, np.floating, np.integer)):
                logs[metric] = float(np.asarray(_ops.allreduce(
                    np.array(value, dtype=np.float64), op=Average,
                    name=f"metric.{metric}")))

    def on_epoch_end(self, epoch, logs=None):
        self._average_metrics_in_place(logs)


class LearningRateScheduleCallbackImpl:
    """Multiply the lr by ``multiplier`` over [start_epoch, end_epoch)
    (reference: keras/callbacks.py LearningRateScheduleCallback)."""

    def __init__(self, backend, initial_lr, multiplier, start_epoch=0,
                 end_epoch=None, staircase=True, momentum_correction=True,
                 steps_per_epoch=None, *args):
        super().__init__(*args)
        self.backend = backend
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = None
        if initial_lr is None:
            raise ValueError("initial_lr is required")
        if callable(multiplier):
            self.staircase = False
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch):
        return self.start_epoch <= epoch and \
            (self.end_epoch is None or epoch < self.end_epoch)

    def _set_lr(self, lr):
        self.model.optimizer.learning_rate = lr

    def _get_lr(self):
        return float(np.asarray(
            self.model.optimizer.learning_rate))

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        if self.steps_per_epoch is None:
            raise ValueError(
                "steps_per_epoch is required for non-staircase "
                "schedules")
        epoch = self.current_epoch + float(batch) / self.steps_per_epoch
        self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self._get_lr()


class LearningRateWarmupCallbackImpl(LearningRateScheduleCallbackImpl):
    """Gradual lr warmup from lr/size to lr over warmup_epochs
    (reference: keras/callbacks.py LearningRateWarmupCallback; the
    Goyal et al. linear-scaling warmup)."""

    def __init__(self, backend, initial_lr, warmup_epochs=5,
                 momentum_correction=True, steps_per_epoch=None,
                 verbose=0, *args):
        def multiplier(epoch):
            size = basics.size()
            return 1.0 / size + epoch * (1.0 - 1.0 / size) / warmup_epochs

        super().__init__(backend, initial_lr, multiplier,
                         start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch, *args)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0 and \
                basics.rank() == 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._get_lr()}.")


class BestModelCheckpointImpl:
    """ModelCheckpoint that only saves on rank 0, after averaging the
    monitored metric (reference: keras/callbacks.py:151
    BestModelCheckpoint)."""

    def __init__(self, *args, **kwargs):
        if kwargs.get("save_best_only") is False:
            raise ValueError(
                "BestModelCheckpoint requires save_best_only=True")
        kwargs["save_best_only"] = True
        super().__init__(*args, **kwargs)

    def on_epoch_end(self, epoch, logs=None):
        if basics.rank() == 0:
            super().on_epoch_end(epoch, logs)
