"""Ray orchestrator integration (reference: horovod/ray/runner.py —
``RayExecutor`` placing one worker actor per slot, a ``Coordinator``
that collects hostnames into the rank env contract, and the elastic
variant over the Ray autoscaler in ray/elastic.py:36-61).

The coordination logic (slot planning, env contract, rendezvous
wiring) is pure Python and unit-testable without Ray; only actor
placement touches the ``ray`` package, which is imported lazily so the
module loads in environments without Ray installed.
"""

from .runner import Coordinator, RayExecutor
from .elastic import ElasticRayExecutor, RayHostDiscovery

__all__ = ["RayExecutor", "Coordinator", "ElasticRayExecutor",
           "RayHostDiscovery"]
