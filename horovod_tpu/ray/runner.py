"""RayExecutor: run horovod_tpu training over Ray actors.

Reference: ray/runner.py — ``Coordinator`` (:178-248) collects each
actor's hostname, computes the rank env contract per slot, and points
every worker at the rendezvous; ``RayExecutor`` (:250+) creates one
actor per slot (colocated per node) and drives setup/execution.
"""

import logging
import socket
from collections import OrderedDict, defaultdict
from typing import Callable, Dict, List, Optional

from ..runner.hosts import HostInfo, get_host_assignments, slot_env_vars
from ..runner.http_server import RendezvousServer, find_ports

logger = logging.getLogger("horovod_tpu.ray")


class Coordinator:
    """Collects worker hostnames and hands out the env contract
    (reference: ray/runner.py:178-248)."""

    def __init__(self):
        self.hostnames_by_rank: "OrderedDict[str, List[int]]" = \
            OrderedDict()

    @property
    def world_size(self) -> int:
        return sum(len(v) for v in self.hostnames_by_rank.values())

    @property
    def node_id_by_rank(self) -> Dict[int, int]:
        out = {}
        for node_id, ranks in enumerate(self.hostnames_by_rank.values()):
            for r in ranks:
                out[r] = node_id
        return out

    def register(self, hostname: str, world_rank: int):
        self.hostnames_by_rank.setdefault(hostname, []).append(world_rank)

    def finalize_registration(self) -> Dict[int, Dict[str, str]]:
        """Returns {world_rank: env_vars} for every registered worker."""
        hosts = [HostInfo(h, len(ranks))
                 for h, ranks in self.hostnames_by_rank.items()]
        np = self.world_size
        slots = get_host_assignments(hosts, np, np)
        # Map computed slots back onto the registered world ranks
        # host-major, same ordering as registration.
        env_by_rank: Dict[int, Dict[str, str]] = {}
        slot_iter = iter(slots)
        for hostname, ranks in self.hostnames_by_rank.items():
            for world_rank in ranks:
                env_by_rank[world_rank] = slot_env_vars(next(slot_iter))
        return env_by_rank


class RayExecutor:
    """Drive ``num_workers`` horovod_tpu workers as Ray actors
    (reference: ray/runner.py:250+ — simplified API: start(),
    run(fn, args), execute(fn), shutdown())."""

    def __init__(self, num_workers: int, cpus_per_worker: int = 1,
                 use_gpu: bool = False, gpus_per_worker: int = 0,
                 env_vars: Optional[Dict[str, str]] = None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.env_vars = dict(env_vars or {})
        self.workers = []
        self._server: Optional[RendezvousServer] = None

    # -- actor plumbing (requires ray) ---------------------------------
    def start(self):
        import ray

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self):
                self._result = None

            def hostname(self):
                return socket.gethostname()

            def set_env(self, env):
                import os
                os.environ.update(env)

            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        self.workers = [Worker.remote() for _ in range(self.num_workers)]
        coordinator = Coordinator()
        hostnames = ray.get([w.hostname.remote() for w in self.workers])
        for rank, hostname in enumerate(hostnames):
            coordinator.register(hostname, rank)
        env_by_rank = coordinator.finalize_registration()

        from ..runner import job_secret
        self._secret = job_secret.make_secret_key()
        self._server = RendezvousServer(secret=self._secret)
        rendezvous_port = self._server.start()
        self._server.init({})
        driver_ip = ray.util.get_node_ip_address() \
            if hasattr(ray.util, "get_node_ip_address") else \
            socket.gethostbyname(socket.gethostname())
        coord_port, ctrl_port = find_ports(2)
        rank0_host = hostnames[0]
        common = {
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": driver_ip,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
            "HOROVOD_CONTROLLER": "tcp",
            job_secret.ENV: self._secret,
            "HOROVOD_TPU_COORDINATOR": f"{rank0_host}:{coord_port}",
            "HOROVOD_CONTROLLER_ADDR": f"{rank0_host}:{ctrl_port}",
        }
        common.update(self.env_vars)
        ray.get([
            w.set_env.remote({**common, **env_by_rank[rank]})
            for rank, w in enumerate(self.workers)])

    def run(self, fn: Callable, args=None, kwargs=None) -> List:
        import ray
        return ray.get([
            w.execute.remote(fn, *(args or ()), **(kwargs or {}))
            for w in self.workers])

    def execute(self, fn: Callable) -> List:
        return self.run(fn)

    def shutdown(self):
        import ray
        for w in self.workers:
            ray.kill(w)
        self.workers = []
        if self._server is not None:
            self._server.stop()
            self._server = None
