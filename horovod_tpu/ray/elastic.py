"""Elastic training over Ray (reference: ray/elastic.py —
``RayHostDiscovery`` reads the autoscaler's live node set :36-61;
``ElasticRayExecutor`` wires it into the elastic driver)."""

import logging
from collections import OrderedDict
from typing import Dict, Optional

from ..runner.elastic.discovery import HostDiscovery

logger = logging.getLogger("horovod_tpu.ray")


class RayHostDiscovery(HostDiscovery):
    """Maps Ray's alive-node view to {hostname: slots} (reference:
    ray/elastic.py:36-61)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        import ray
        host_slots = OrderedDict()
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            hostname = node.get("NodeManagerHostname") or \
                node.get("NodeManagerAddress")
            resources = node.get("Resources", {})
            if self.use_gpu:
                slots = int(resources.get("GPU", 0) // self.gpus_per_slot)
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            if slots > 0:
                host_slots[hostname] = slots
        return host_slots


class ElasticRayExecutor:
    """Elastic run over Ray nodes: the elastic driver spawns workers via
    ssh onto Ray hosts as membership changes (reference:
    ray/elastic.py ElasticRayExecutor, simplified to the command-launch
    path shared with horovodrun)."""

    def __init__(self, min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 elastic_timeout: float = 600,
                 use_gpu: bool = False, cpus_per_slot: int = 1,
                 override_discovery: Optional[HostDiscovery] = None):
        self.discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_slot)
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.elastic_timeout = elastic_timeout

    def run_command(self, command, **kwargs):
        from ..runner.elastic_run import launch_elastic
        return launch_elastic(
            command, discovery=self.discovery, np=self.min_np,
            min_np=self.min_np, max_np=self.max_np,
            reset_limit=self.reset_limit,
            elastic_timeout=self.elastic_timeout, **kwargs)
