"""MXNet binding placeholder.

The reference ships an MXNet binding (reference: horovod/mxnet/ —
DistributedOptimizer, gluon DistributedTrainer, broadcast_parameters).
MXNet reached end-of-life upstream (attic'd by Apache in 2023) and is
not installed in TPU images; this module keeps the import surface with
an actionable error instead of silently missing.
"""

_MSG = ("horovod_tpu.mxnet requires the 'mxnet' package, which is not "
        "installed (MXNet is end-of-life upstream). Use the JAX "
        "(horovod_tpu.jax), PyTorch (horovod_tpu.torch) or Keras "
        "(horovod_tpu.keras) bindings instead.")

try:
    import mxnet  # noqa: F401
    _HAS_MXNET = True
except ImportError:
    _HAS_MXNET = False

if not _HAS_MXNET:
    def __getattr__(name):
        raise ImportError(_MSG)
