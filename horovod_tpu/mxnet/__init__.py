"""MXNet binding — FORMALLY DESCOPED (see docs/mxnet_descope.md).

The reference ships an MXNet binding (reference: horovod/mxnet/ —
DistributedOptimizer, gluon DistributedTrainer, broadcast_parameters).
MXNet reached end-of-life upstream (attic'd by Apache in September
2023), has no TPU path, and is not installable in TPU images, so this
framework deliberately does not implement the binding; this module
keeps the import surface with an actionable error instead of a silent
gap.  Migration: gluon → horovod_tpu.keras, module API →
horovod_tpu.torch (full rationale in docs/mxnet_descope.md).
"""

_MSG = ("horovod_tpu.mxnet is formally descoped: MXNet is end-of-life "
        "upstream (Apache attic, Sept 2023) and has no TPU path. Use "
        "the JAX (horovod_tpu.jax), PyTorch (horovod_tpu.torch) or "
        "Keras (horovod_tpu.keras) bindings instead; see "
        "docs/mxnet_descope.md for the migration table.")

try:
    import mxnet  # noqa: F401
    _HAS_MXNET = True
except ImportError:
    _HAS_MXNET = False

if not _HAS_MXNET:
    def __getattr__(name):
        raise ImportError(_MSG)
