"""Job-secret-HMAC-guarded HTTP lookup endpoint for the serving plane.

Reuses the rendezvous KV server's handler plumbing exactly like the
/metrics//status//profile endpoints do (common/metrics.py
``MetricsServer`` is the template): same ``KVStoreHandler`` base, same
HMAC guard (``job_secret`` — embeddings are trained model state, never
an unauthenticated sidechannel), same no-secret-serves-openly
unit-test semantics, same 404-bare / 403-unsigned / 200-signed
contract the auth-parity tests pin.

Protocol — ``POST /lookup`` with a JSON body::

    {"table": "cat0", "ids": [3, 5, 3]}                 # raw rows
    {"table": "cat0", "ids": [...], "offsets": [0, 2],
     "mode": "sum"}                                     # pooled bags

answers 200 with ``{"table", "step", "rows"}`` where ``step`` is the
served-step stamp (every row is the committed value at exactly that
training step), 400 on malformed bodies or out-of-range ids, 404 on
unknown tables (or when no replica is wired), and 503 when the
staleness bound rejects the read (the freshness contract surfaced as
backpressure).  ``GET /freshness`` reports the served/latest steps
and table inventory.
"""

import json
import logging
import threading
from typing import Optional

from .replica import ServingReplica, StalenessError

logger = logging.getLogger("horovod_tpu.serve")

SERVICE_UNAVAILABLE = 503


class ServeServer:
    """Threaded HTTP front end over one :class:`ServingReplica`."""

    def __init__(self, replica: Optional[ServingReplica],
                 port: int = 0, secret: Optional[str] = None):
        from http.server import ThreadingHTTPServer

        from ..runner import job_secret
        from ..runner.http_server import (BAD_REQUEST, NOT_FOUND, OK,
                                          KVStoreHandler, ReplayCache)

        self._replica = replica
        server_self = self

        class _ServeHandler(KVStoreHandler):
            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not self._authorized():
                    return
                path = self.path.split("?", 1)[0].rstrip("/")
                replica = server_self._replica
                if path != "/freshness" or replica is None:
                    self.send_response(NOT_FOUND)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                served, latest = replica.freshness()
                self._send_json(OK, {
                    "served_step": served,
                    "latest_step": latest,
                    "tables": replica.table_names(),
                })

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    self._reject(BAD_REQUEST)
                    return
                if not self._precheck_put(length):
                    return
                body = self.rfile.read(length)
                if not self._authorized(body):
                    return
                path = self.path.split("?", 1)[0].rstrip("/")
                replica = server_self._replica
                if path != "/lookup" or replica is None:
                    self.send_response(NOT_FOUND)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                try:
                    req = json.loads(body.decode("utf-8"))
                    table = req["table"]
                    ids = req["ids"]
                except (ValueError, KeyError, UnicodeDecodeError, TypeError):
                    self._reject(BAD_REQUEST)
                    return
                try:
                    if req.get("offsets") is not None:
                        rows, step = replica.embedding_bag(
                            table, ids, req["offsets"],
                            mode=req.get("mode", "sum"))
                    else:
                        rows, step = replica.lookup(table, ids)
                except StalenessError as e:
                    self._send_json(SERVICE_UNAVAILABLE,
                                    {"error": str(e)})
                    return
                except KeyError:
                    self.send_response(NOT_FOUND)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                except (IndexError, ValueError, TypeError) as e:
                    logger.debug("bad lookup request: %s", e)
                    self._reject(BAD_REQUEST)
                    return
                self._send_json(OK, {
                    "table": table,
                    "step": step,
                    "rows": rows.tolist(),
                })

            def do_PUT(self):
                self._reject(405)

            def do_DELETE(self):
                self._reject(405)

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                          _ServeHandler)
        self._httpd.kvstore = None
        self._httpd.secret = secret if secret is not None \
            else job_secret.current()
        self._httpd.replay_cache = ReplayCache()
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-serve-http",
            daemon=True)
        self._thread.start()
        logger.debug("serve endpoint listening on %d", self.port)

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
