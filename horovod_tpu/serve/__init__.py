"""Online embedding serving plane: snapshot-consistent reads at QPS
while training continues.

The subsystem closes the trained-to-served loop that Check-N-Run
(NSDI '22) describes: the trainer's committed checkpoint manifests +
RowDelta chains (``horovod_tpu/checkpoint/``) double as the serving
plane's consistency boundary and incremental update channel.  A
:class:`ServingReplica` bootstraps from the latest committed manifest,
tails newly committed steps, and atomically flips immutable snapshots
so every read observes exactly one committed training step;
:class:`ServeServer` fronts it with the job-secret-HMAC HTTP contract
shared with /metrics//status//profile.

In-process use (the ``hvd.serve`` API)::

    import horovod_tpu as hvd
    plane = hvd.serve.start(ckpt_dir)         # bootstrap + tail + HTTP
    rows, step = plane.replica.lookup("cat0", [3, 5, 3])
    ...
    plane.stop()

Knobs: ``HOROVOD_SERVE_MAX_STALENESS_STEPS`` (reject reads when the
replica lags the freshest commit by more than N steps),
``HOROVOD_SERVE_POLL_SECONDS`` (manifest tail cadence),
``HOROVOD_SERVE_PORT`` (HTTP port; 0 = ephemeral).  See
docs/serving.md.
"""

from typing import Optional

from ..common import env as _env
from ..common.env import env_int
from .replica import ServingReplica, StalenessError
from .server import ServeServer

__all__ = ["ServingReplica", "StalenessError", "ServeServer",
           "ServePlane", "start"]


class ServePlane:
    """One running serving plane: replica + tail thread + HTTP
    endpoint, stopped together."""

    def __init__(self, replica: ServingReplica,
                 server: Optional[ServeServer]):
        self.replica = replica
        self.server = server

    @property
    def port(self) -> Optional[int]:
        return self.server.port if self.server is not None else None

    def stop(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
        self.replica.stop()


def start(directory: str, port: Optional[int] = None,
          secret: Optional[str] = None, http: bool = True,
          tail: bool = True) -> ServePlane:
    """Bootstrap a replica from ``directory``'s latest committed step
    and (by default) start the tail thread and the HTTP lookup
    endpoint.  Raises CheckpointNotFoundError when nothing has been
    committed yet."""
    replica = ServingReplica(directory)
    replica.bootstrap()
    if tail:
        replica.start()
    server = None
    if http:
        if port is None:
            port = env_int(_env.HOROVOD_SERVE_PORT, 0)
        server = ServeServer(replica, port=port, secret=secret)
    return ServePlane(replica, server)
