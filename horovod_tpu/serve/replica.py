"""Snapshot-consistent serving replica over committed checkpoints.

The replica turns the durable training artifact — committed manifests
plus RowDelta chains (``horovod_tpu/checkpoint/``) — into an online,
read-only embedding lookup plane, the trained-to-served pipeline of
Check-N-Run (Eisenman et al., NSDI '22).  The committed MANIFEST is
the one consistency boundary the trainer already guarantees
(all-or-nothing, arbiter-published), so the replica reuses it as the
read-side snapshot boundary, the same capture/persist split CheckFreq
(Mohan et al., FAST '21) draws on the write side:

* **Bootstrap** — ``restore_latest`` replays full base + delta chain
  (falling back past corrupt steps exactly like a restarted trainer
  would) and assembles every ``sparse/<table>/rows`` prefix into a
  dense in-memory table.

* **Tail** — a poll thread watches ``committed_steps()``; each newly
  committed step whose ``delta_of`` is the step currently served is
  applied *incrementally* (only the touched rows cross from disk), any
  other gap (missed steps, resize, corrupt link) triggers a full
  rebase through ``restore``.

* **Atomic flip** — every advance builds a fresh immutable
  :class:`_Snapshot` (copy-on-write per affected table) and installs
  it with ONE reference assignment.  Readers grab ``self._snap`` once
  per request, so a read observes exactly one committed training step
  — a torn mid-apply view is structurally impossible, not just
  locked away.  The ``serve.delta_apply`` failpoint sits BETWEEN build
  and flip so the chaos drills can kill a replica at the worst moment
  and assert reads before/after both see whole committed steps.

* **Freshness plane** — ``hvd_serve_freshness_steps`` / ``_seconds``
  gauges (freshest committed step minus served step), per-request
  served-step stamping, and staleness-bound rejection
  (``HOROVOD_SERVE_MAX_STALENESS_STEPS``): a replica that fell too far
  behind starts refusing reads rather than silently serving stale
  rows.

See docs/serving.md for the architecture and freshness semantics.
"""

import logging
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint import CheckpointManager, RowDelta
from ..checkpoint.delta import assemble_table
from ..common import env as _env
from ..common import failpoints as _fp
from ..common import flight_recorder as _fr
from ..common import metrics

logger = logging.getLogger("horovod_tpu.serve")

_FRESH_STEPS = metrics.gauge(
    "hvd_serve_freshness_steps",
    "Freshest committed training step minus the step the replica "
    "currently serves (0 = fully caught up)")
_FRESH_SECONDS = metrics.gauge(
    "hvd_serve_freshness_seconds",
    "Wall seconds the replica has been behind the freshest committed "
    "step (0 while caught up)")
_LOOKUP_SECONDS = metrics.histogram(
    "hvd_serve_lookup_seconds",
    "Serving read latency by op (lookup = raw rows, bag = pooled "
    "EmbeddingBag read)")
_ROWS = metrics.counter(
    "hvd_serve_rows_total",
    "Rows served, split by whether the row was last written by the "
    "bootstrap/rebase base image or an incrementally applied delta")
_FLIPS = metrics.counter(
    "hvd_serve_snapshot_flips_total",
    "Atomic snapshot installs by kind (bootstrap / delta / rebase)")
_REJECTS = metrics.counter(
    "hvd_serve_rejects_total",
    "Reads refused, by reason (staleness = freshness lag exceeded "
    "HOROVOD_SERVE_MAX_STALENESS_STEPS)")

# Sparse-table checkpoint items are named sparse/<table>/rows.r<rank>
# (ShardedEmbedding.item_name); the prefix is the per-table assembly
# key shared with assemble_table.
_ITEM_RE = re.compile(r"^sparse/(.+)/rows\.r\d+$")


class StalenessError(RuntimeError):
    """Read refused: the replica is farther behind the freshest
    committed step than HOROVOD_SERVE_MAX_STALENESS_STEPS allows."""


class _Snapshot:
    """One immutable served view: exactly one committed step's tables.

    ``delta_mask[name][row]`` is True when the row's current value was
    written by an incremental delta apply (vs the base image this
    snapshot line descends from) — the source attribution behind
    ``hvd_serve_rows_total{source=base|delta}``.
    """

    __slots__ = ("step", "tables", "delta_mask")

    def __init__(self, step: int, tables: Dict[str, np.ndarray],
                 delta_mask: Dict[str, np.ndarray]):
        self.step = step
        self.tables = tables
        self.delta_mask = delta_mask


def _full_snapshot(step: int, items: Dict[str, object]) -> "_Snapshot":
    """A from-scratch snapshot: every sparse table assembled to full
    coverage, delta masks cleared (everything is 'base' again)."""
    tables: Dict[str, np.ndarray] = {}
    masks: Dict[str, np.ndarray] = {}
    for name in _split_by_table(items):
        table = assemble_table(items, "sparse/%s/rows" % name)
        if table is None:  # pragma: no cover - split guarantees a hit
            continue
        tables[name] = table
        masks[name] = np.zeros(table.shape[0], dtype=bool)
    return _Snapshot(step, tables, masks)


def _split_by_table(items: Dict[str, object]) -> Dict[str, Dict[str, object]]:
    """Group checkpoint items by embedding-table name, dropping
    anything that is not a sparse-table shard (dense replicated state
    has no read-side meaning here)."""
    by_table: Dict[str, Dict[str, object]] = {}
    for name, item in items.items():
        m = _ITEM_RE.match(name)
        if m is not None:
            by_table.setdefault(m.group(1), {})[name] = item
    return by_table


class ServingReplica:
    """Read-only embedding server over a trainer's checkpoint
    directory.  All reads are lock-free against a single immutable
    snapshot reference; only the tail thread (or explicit
    ``poll_once`` calls) installs new snapshots."""

    def __init__(self, directory: str):
        self.directory = directory
        # Read-only manager: world_size=1 needs no coordinator, and
        # keep=None means this replica never garbage-collects the
        # trainer's steps out from under it.
        self._mgr = CheckpointManager(directory, rank=0, world_size=1,
                                      keep=None)
        self._snap: Optional[_Snapshot] = None
        self._latest_known: Optional[int] = None
        self._behind_since: Optional[float] = None
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # snapshot lifecycle (tail side)
    # ------------------------------------------------------------------
    def bootstrap(self) -> int:
        """Load the newest valid committed step (full base + delta
        chain, falling back past corrupt steps) and install it as the
        first served snapshot.  Returns the served step.  Raises
        :class:`~..checkpoint.CheckpointNotFoundError` when nothing
        has ever been committed."""
        step, items = self._mgr.restore_latest()
        self._install(_full_snapshot(step, items), "bootstrap")
        with self._poll_lock:
            self._refresh_freshness()
        return step

    def poll_once(self) -> int:
        """Tail newly committed steps: apply every step the trainer
        committed since the served one (incremental delta apply when
        the chain lines up, full rebase otherwise), refresh the
        freshness gauges, and return how many snapshots were
        installed.  A corrupt step is skipped — the replica keeps
        serving the last good snapshot and never regresses."""
        with self._poll_lock:
            advanced = 0
            snap = self._snap
            if snap is None:
                raise RuntimeError("poll_once before bootstrap")
            for step in self._mgr.committed_steps():
                if step <= self._snap.step:
                    continue
                try:
                    if self._try_advance(step):
                        advanced += 1
                except Exception as e:  # corrupt link, torn disk, ...
                    logger.warning(
                        "serve: cannot advance to committed step %d "
                        "(%s); still serving step %d", step, e,
                        self._snap.step)
            self._refresh_freshness()
            return advanced

    def _try_advance(self, step: int) -> bool:
        """Build the snapshot for one newly committed ``step`` and
        atomically install it.  Returns False when a failpoint dropped
        the flip (the old snapshot stays live — the torn-apply drill's
        'kill between build and install' window)."""
        snap = self._snap
        items, parent = self._mgr.step_items(step)
        new = mode = None
        if parent is not None and parent == snap.step:
            try:
                new = self._apply_delta(snap, step, items)
                mode = "delta"
            except KeyError:
                pass  # table unknown to this snapshot line: rebase
        elif parent is None:
            new = _full_snapshot(step, items)
            mode = "rebase"
        if new is None:
            # The step's own items do not extend what we serve (missed
            # steps, a resize, a new table) — replay its whole
            # base→tip chain.
            new = _full_snapshot(step, self._mgr.restore(step))
            mode = "rebase"
        # The torn-apply window: the new snapshot exists but is NOT
        # yet visible.  A crash here must leave readers on the old
        # whole-step view; "drop" models a flip that never lands.
        if _fp.ENABLED:
            if _fp.maybe_fail("serve.delta_apply") == "drop":
                return False
        self._install(new, mode)
        return True

    @staticmethod
    def _apply_delta(snap: "_Snapshot", step: int,
                     items: Dict[str, object]) -> "_Snapshot":
        """Copy-on-write application of one committed step's RowDelta
        items on top of ``snap``: only tables the step touched are
        copied, untouched tables are shared by reference (immutable by
        convention — readers never write)."""
        tables = dict(snap.tables)
        masks = dict(snap.delta_mask)
        for name, shard_items in _split_by_table(items).items():
            base = tables.get(name)
            deltas = [it for it in shard_items.values()
                      if isinstance(it, RowDelta)]
            if base is None:
                # A table born after bootstrap: its delta carries all
                # its touched rows, but without a base image the only
                # safe view is a full assembly next rebase; skip.
                logger.warning("serve: step %d touches unknown table "
                               "%r; rebase required", step, name)
                raise KeyError(name)
            table = base.copy()
            mask = masks[name].copy()
            for d in deltas:
                d.apply_to(table)
                mask[d.rows] = True
            tables[name] = table
            masks[name] = mask
        return _Snapshot(step, tables, masks)

    def _install(self, snap: "_Snapshot", mode: str):
        self._snap = snap  # THE atomic flip: one reference assignment
        _FLIPS.inc(kind=mode)
        if _fr.ENABLED:
            _fr.record(_fr.SERVE, phase="flip", step=snap.step,
                       mode=mode, tables=len(snap.tables))

    def _refresh_freshness(self):
        """Update the freshness gauges (called with _poll_lock
        held)."""
        steps = self._mgr.committed_steps()
        latest = steps[-1] if steps else None
        self._latest_known = latest
        snap = self._snap
        if snap is None or latest is None:
            return
        lag = max(0, latest - snap.step)
        _FRESH_STEPS.set(float(lag))
        if lag == 0:
            self._behind_since = None
            _FRESH_SECONDS.set(0.0)
        else:
            now = time.monotonic()
            if self._behind_since is None:
                self._behind_since = now
            _FRESH_SECONDS.set(now - self._behind_since)

    # ------------------------------------------------------------------
    # tail thread
    # ------------------------------------------------------------------
    def start(self):
        """Start the background tail thread (bootstrap must have
        happened)."""
        if self._snap is None:
            raise RuntimeError("start before bootstrap")
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._tail_loop,
                                        name="hvd-serve-tail",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def _tail_loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                # Serving must outlive any tail hiccup (trainer mid-
                # write, directory briefly unreadable, ...).
                logger.exception("serve: poll failed; still serving")
            self._stop.wait(_env.serve_poll_seconds())

    # ------------------------------------------------------------------
    # read side (lock-free)
    # ------------------------------------------------------------------
    def freshness(self) -> Tuple[int, Optional[int]]:
        """(served step, freshest known committed step)."""
        snap = self._snap
        if snap is None:
            raise RuntimeError("freshness before bootstrap")
        return snap.step, self._latest_known

    def table_names(self) -> List[str]:
        snap = self._snap
        return sorted(snap.tables) if snap is not None else []

    def _check_staleness(self, snap: "_Snapshot"):
        bound = _env.serve_max_staleness_steps()
        if not bound:
            return
        latest = self._latest_known
        lag = 0 if latest is None else max(0, latest - snap.step)
        if lag > bound:
            _REJECTS.inc(reason="staleness")
            raise StalenessError(
                "replica serves step %d but step %d is committed "
                "(lag %d > bound %d)" % (snap.step, latest, lag, bound))

    def lookup(self, table: str, ids) -> Tuple[np.ndarray, int]:
        """Batch id lookup against the current snapshot.  Returns
        ``(rows, served_step)`` — the step stamp is the consistency
        contract: every returned row is the committed value at exactly
        that training step.  Raises KeyError (unknown table),
        IndexError (id out of range), :class:`StalenessError`."""
        t0 = time.perf_counter()
        snap = self._snap
        if snap is None:
            raise RuntimeError("lookup before bootstrap")
        self._check_staleness(snap)
        arr = snap.tables[table]
        ids = np.asarray(ids, np.int64)
        rows = arr[ids]  # fancy index: a copy, detached from the snap
        n_delta = int(np.count_nonzero(snap.delta_mask[table][ids]))
        if n_delta:
            _ROWS.inc(float(n_delta), source="delta")
        if len(ids) - n_delta:
            _ROWS.inc(float(len(ids) - n_delta), source="base")
        _LOOKUP_SECONDS.observe(time.perf_counter() - t0, op="lookup")
        return rows, snap.step

    def embedding_bag(self, table: str, ids, offsets,
                      mode: str = "sum") -> Tuple[np.ndarray, int]:
        """Pooled EmbeddingBag read (the DLRM bag shape, torch offsets
        convention: example i owns ids[offsets[i]:offsets[i+1]]).
        Returns ``(pooled, served_step)``."""
        if mode not in ("sum", "mean"):
            raise ValueError("mode must be 'sum' or 'mean'")
        t0 = time.perf_counter()
        rows, step = self.lookup(table, ids)
        offsets = np.asarray(offsets, np.int64)
        sizes = np.diff(np.concatenate([offsets, [rows.shape[0]]]))
        if (sizes < 0).any():
            raise ValueError("offsets must be non-decreasing")
        seg = np.repeat(np.arange(len(offsets)), sizes)
        out = np.zeros((len(offsets), rows.shape[1]), rows.dtype)
        np.add.at(out, seg, rows)
        if mode == "mean":
            out /= np.maximum(sizes, 1)[:, None]
        _LOOKUP_SECONDS.observe(time.perf_counter() - t0, op="bag")
        return out, step
