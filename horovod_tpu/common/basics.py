"""Process-level runtime state and the ``hvd.*`` basics API.

TPU-native replacement for the reference's ``HorovodBasics`` ctypes bridge
(reference: common/basics.py:22-258 backed by the extern "C" query API in
operations.cc:708-896).  Instead of loading a compiled shared library per
framework, horovod_tpu keeps one process-wide runtime whose data plane is
XLA; the optional C++ core accelerates the control plane only.

Topology model (TPU-first):
  * a *rank* is a launched process (one per TPU-VM host, or one per chip
    when the launcher splits hosts into per-chip slots);
  * each rank owns ``jax.local_devices()`` chips;
  * device-level parallelism inside a rank is expressed through the mesh
    (``horovod_tpu.parallel``), compiled by XLA — not by more processes.
"""

import atexit
import logging
import os
import threading
from typing import List, Optional, Sequence

from . import env as env_mod
from .env import Knobs, RankInfo
from .exceptions import NotInitializedError

logger = logging.getLogger("horovod_tpu")

# Reduction op constants, matching the reference's enum values
# (reference: common/basics.py Average/Sum/Adasum constants + common.h).
Average = "Average"
Sum = "Sum"
Adasum = "Adasum"
Min = "Min"
Max = "Max"
Product = "Product"


class ProcessSet:
    """A subset of ranks forming their own collective group.

    The analog of ``hvd.init(comm=[ranks])`` sub-communicators
    (reference: common/basics.py:33-65, controller.h:112-117).  The global
    process set contains every rank.
    """

    def __init__(self, ranks: Optional[Sequence[int]] = None):
        self.ranks: Optional[List[int]] = (
            sorted(ranks) if ranks is not None else None)
        self.process_set_id: int = 0 if ranks is None else -1

    def included(self, rank: int) -> bool:
        return self.ranks is None or rank in self.ranks

    def size(self) -> int:
        state = _state()
        return (state.rank_info.size if self.ranks is None
                else len(self.ranks))

    def rank(self) -> int:
        state = _state()
        if self.ranks is None:
            return state.rank_info.rank
        return self.ranks.index(state.rank_info.rank)

    def __repr__(self):
        return f"ProcessSet(ranks={self.ranks or 'global'})"


global_process_set = ProcessSet(None)


class HorovodTpuState:
    """Per-process singleton (analog of HorovodGlobalState,
    reference: common/global_state.h:43-132)."""

    def __init__(self):
        self.initialized = False
        self.init_lock = threading.Lock()
        self.rank_info = RankInfo()
        self.knobs = Knobs()
        self.process_sets: List[ProcessSet] = [global_process_set]
        # Monotonic: ids are NEVER reused.  Deriving the next id from
        # len(process_sets) would hand a removed set's id to a new set
        # while another registered set still holds it — two live sets
        # sharing an id collides every (psid, name)-keyed coordinator
        # structure.  Advances identically on every rank because
        # add/remove_process_set are collective calls (reference
        # contract, process_set.h).
        self.next_process_set_id = 1  # 0 = global
        self.backend = None          # ops data-plane backend
        self.runtime = None          # background negotiation runtime
        self.timeline = None
        self.metrics_server = None   # /metrics HTTP endpoint (opt-in)
        self.parameter_manager = None   # legacy HOROVOD_AUTOTUNE GP
        self.tune_session = None     # autotune-then-freeze (rank 0)
        self.elastic_enabled = False
        self.host_messages = None    # elastic host-update queue
        self.is_homogeneous = True
        self.distributed_client_owned = False
        # Monotonic per-process init counter (observability; NOT safe
        # as a cross-rank namespace — freshly spawned elastic workers
        # start at 0 while survivors are at N).
        self.init_generation = 0

    def require_init(self):
        if not self.initialized:
            raise NotInitializedError()


_global_state = HorovodTpuState()


def _state() -> HorovodTpuState:
    return _global_state


def _maybe_init_jax_distributed(info: RankInfo):
    """Join the multi-controller JAX world when launched with size > 1.

    On TPU pods this wires the coordination service over DCN (the analog
    of the reference's rendezvous in gloo/gloo_context.cc:63-84, except
    the bulk data plane then rides compiled ICI collectives).  On CPU the
    gloo cross-process collective implementation is selected so the same
    code path is testable without TPU hardware.
    """
    import jax

    coordinator = env_mod.env_str_opt(env_mod.HOROVOD_TPU_COORDINATOR)
    if coordinator is None:
        return False
    # Must not touch the backend (jax.devices/process_count) before
    # jax.distributed.initialize — probe the distributed client state
    # directly instead.
    try:
        from jax._src import distributed as _dist
        already = _dist.global_state.client is not None
    except Exception:
        already = False
    if already:
        return False
    if env_mod.env_str("JAX_PLATFORMS").startswith("cpu") or \
            env_mod.env_str_opt("HOROVOD_TPU_FORCE_CPU"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.config.update("jax_platforms", "cpu")
    if _state().knobs.elastic:
        # A peer hard-dying must surface as HorovodInternalError and
        # unwind to the elastic retry loop — without this flag the
        # coordination service's error polling TERMINATES survivor
        # processes outright (client.h fatal on peer heartbeat
        # timeout), so recovery never runs.
        try:
            jax.config.update("jax_enable_recoverability", True)
        except AttributeError:
            # jax 0.4.x: no recoverability support — survivors of a
            # peer HARD-death die with it (the coordination service
            # marks the dead task errored; propagating that error is
            # unconditionally process-fatal in this jaxlib: the
            # default missed-heartbeat/error callback LOG(FATAL)s,
            # and installing a custom python callback crashes the
            # error-poll thread with std::bad_cast; a barrier-free
            # client drop makes CLEAN departures look like failures
            # instead — measured, not speculation).  Death-recovery
            # elastic tests skip on such jax versions; see
            # jax_peer_death_recoverable() in tests/test_elastic_run.py.
            pass
    heartbeat = env_mod.env_str_opt("HOROVOD_JAX_HEARTBEAT_TIMEOUT")
    kwargs = {}
    if heartbeat:
        kwargs["heartbeat_timeout_seconds"] = int(heartbeat)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=info.size,
        process_id=info.rank, **kwargs)
    return True


def init(comm=None, process_sets=None):
    """Initialize horovod_tpu.

    ``comm`` may be a list of ranks forming a sub-world (reference
    semantics of ``hvd.init(comm=[0,1])``, common/basics.py:33-65); mpi4py
    communicators are not supported (no MPI on TPU pods) — the rendezvous
    is the launcher env contract / TPU slice metadata instead.
    """
    state = _state()
    with state.init_lock:
        if state.initialized:
            # Re-init is a no-op for the world, but process sets must
            # NOT be silently dropped: register them now (the
            # reference allows post-init registration via
            # add_process_set; dropping them here left ids at -1 and
            # sent colliding psid=-1 requests — a measured 4-rank
            # wedge, tests/test_stress_protocol.py).
            if process_sets:
                for ps in process_sets:
                    if getattr(ps, "process_set_id", -1) in (-1, None):
                        add_process_set(ps)
            return
        state.knobs = Knobs.from_env()
        # Opt-in lock-order witness (docs/static_analysis.md): arm
        # BEFORE any control-plane object constructs its locks so the
        # whole incarnation's acquisition graph is recorded.
        from . import lockwitness as _lw
        _lw.maybe_enable_from_env()
        if state.knobs.elastic and \
                env_mod.env_str_opt(env_mod.HOROVOD_RENDEZVOUS_ADDR):
            # Elastic worker: rank identity comes from the driver's
            # rendezvous, fresh every epoch (reference:
            # gloo/gloo_context.cc:154-200 elastic rank re-query).
            from ..runner.elastic.worker import (
                RendezvousHostUpdateSource, elastic_rendezvous)
            from . import elastic as elastic_mod
            info = elastic_rendezvous()
            state.elastic_enabled = True
            src = RendezvousHostUpdateSource(
                seed_generation=int(info.get("generation", 0)))
            elastic_mod.set_host_update_source(src)
        state.rank_info = RankInfo.from_env()

        if comm is not None and not hasattr(comm, "Get_rank"):
            ranks = sorted(comm)
            if state.rank_info.launched and ranks:
                # Restrict the world to the given ranks.
                if state.rank_info.rank in ranks:
                    sub_rank = ranks.index(state.rank_info.rank)
                    state.rank_info.rank = sub_rank
                    state.rank_info.size = len(ranks)

        if state.rank_info.size > 1 and \
                env_mod.env_str_opt(
                    env_mod.HOROVOD_TPU_COORDINATOR) is None \
                and env_mod.env_str_opt("HOROVOD_RANK0_ADDR") and \
                env_mod.env_str_opt(env_mod.HOROVOD_RENDEZVOUS_ADDR):
            # Static launch with a remote rank 0: the launcher could
            # not pick valid ports for rank 0's host, so rank 0 picks
            # them here and publishes via the rendezvous KV
            # (runner/endpoints.py).
            from ..runner.endpoints import STATIC_KEY, resolve_endpoints
            from ..runner.http_server import RendezvousClient
            client = RendezvousClient(
                env_mod.env_require(env_mod.HOROVOD_RENDEZVOUS_ADDR),
                int(env_mod.env_require(
                    env_mod.HOROVOD_RENDEZVOUS_PORT)))
            eps = resolve_endpoints(
                client, state.rank_info.rank,
                env_mod.env_require("HOROVOD_RANK0_ADDR"), STATIC_KEY,
                timeout=env_mod.start_timeout())
            os.environ[env_mod.HOROVOD_TPU_COORDINATOR] = \
                eps["coordinator"]
            os.environ["HOROVOD_CONTROLLER_ADDR"] = \
                eps["controller_addr"]

        if state.rank_info.size > 1:
            state.distributed_client_owned = _maybe_init_jax_distributed(
                state.rank_info)

        # Failpoint rank= predicates resolve against the final rank of
        # this incarnation (elastic rendezvous above may have changed
        # the env contract since import time).
        from . import failpoints
        failpoints.set_rank(state.rank_info.rank)

        # Black-box flight recorder: rank-tag events recorded from here
        # on, and install the SIGUSR2 dump hook (no-op off the main
        # thread or when the recorder is disarmed).
        from . import flight_recorder
        flight_recorder.set_rank(state.rank_info.rank)
        if flight_recorder.ENABLED:
            flight_recorder.install_signal_handler()

        # Why-is-it-slow plane: rank-tag the sampling profiler and the
        # SLO evaluator (both armed at import from HOROVOD_PROFILE /
        # HOROVOD_SLO; set_rank is a no-op when disarmed).
        from . import profiler as profiler_mod
        from . import slo as slo_mod
        profiler_mod.set_rank(state.rank_info.rank)
        slo_mod.set_rank(state.rank_info.rank)

        from ..ops.backend import create_backend
        state.backend = create_backend(state)

        from .runtime import BackgroundRuntime
        state.runtime = BackgroundRuntime(state)
        state.runtime.start()

        if state.knobs.timeline:
            from .timeline import Timeline
            state.timeline = Timeline(
                state.knobs.timeline, rank=state.rank_info.rank,
                mark_cycles=state.knobs.timeline_mark_cycles)
            state.runtime.timeline = state.timeline

        if state.knobs.metrics_port is not None and \
                state.metrics_server is None:
            from . import metrics as metrics_mod
            # Per-local-rank offset: with several ranks on one host a
            # fixed port would let only the first binder serve; 0
            # still means "ephemeral" for every rank.
            port = state.knobs.metrics_port
            if port:
                port += state.rank_info.local_rank
            try:
                state.metrics_server = metrics_mod.serve(
                    port=port,
                    cluster_provider=cluster_metrics_snapshot,
                    status_provider=status,
                    profile_provider=profiler_mod.profile_dict)
                logger.info("metrics endpoint on port %d",
                            state.metrics_server.port)
            except (OSError, OverflowError, ValueError):
                # Includes out-of-range ports (bind raises
                # OverflowError, not OSError): a bad observability
                # knob must never take down training.
                logger.warning(
                    "could not start the /metrics endpoint on port %d",
                    port, exc_info=True)

        if process_sets:
            for ps in process_sets:
                add_process_set(ps)

        state.init_generation += 1
        state.initialized = True
        logger.debug("horovod_tpu initialized: rank=%d size=%d local=%d/%d",
                     state.rank_info.rank, state.rank_info.size,
                     state.rank_info.local_rank, state.rank_info.local_size)


def _teardown_jax_distributed():
    """Tear down the jax.distributed client so a later init() can
    re-form the world with a different size (elastic reset; verified
    working on the gloo CPU path and on TPU via the
    coordination-service client restart)."""
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        logger.warning("jax.distributed.shutdown failed",
                       exc_info=True)
    try:
        jax.clear_caches()
        import jax.extend.backend as _jeb
        _jeb.clear_backends()
    except Exception:
        logger.warning("clearing XLA backends failed", exc_info=True)


def shutdown():
    state = _state()
    with state.init_lock:
        if not state.initialized:
            return
        if state.runtime is not None:
            # Quiesce (not detach): halts the cycle loop AND disables
            # recv-thread response dispatch before the backend closes,
            # so a late frame can't execute against a freed ring
            # communicator; the controller attachment itself stays up
            # as the teardown-ordering signal (below).
            state.runtime.quiesce()
        if state.timeline is not None:
            state.timeline.close()
            state.timeline = None
        if state.metrics_server is not None:
            state.metrics_server.stop()
            state.metrics_server = None
        if state.backend is not None and hasattr(state.backend, "close"):
            state.backend.close()
        state.backend = None
        # Teardown ORDER is load-bearing for elastic resets: the jax
        # coordination service (hosted by rank 0) dying under a
        # still-attached client is PROCESS-FATAL for that client
        # (LOG(FATAL) in the disconnect RPC — recoverability does not
        # cover leader loss).  So in elastic mode non-leader ranks
        # disconnect their jax client FIRST, while still attached to
        # the rank-0 controller; rank 0's controller shutdown
        # drain-waits on those attachments, and only then takes the
        # coordination service down.  Elastic-only: recoverable tasks
        # skip jax's client-side shutdown barrier, so the early
        # disconnect returns immediately — in non-elastic mode it
        # would block on the barrier against rank 0, which is itself
        # waiting in the controller drain (a deadlock ridden out by
        # timeouts).
        is_leader = state.rank_info.rank == 0
        if state.distributed_client_owned and not is_leader and \
                state.knobs.elastic:
            _teardown_jax_distributed()
            state.distributed_client_owned = False
        if state.runtime is not None:
            state.runtime.detach()
            state.runtime = None
        state.tune_session = None
        state.parameter_manager = None
        if state.distributed_client_owned:
            _teardown_jax_distributed()
            state.distributed_client_owned = False
        state.initialized = False


atexit.register(shutdown)


def is_initialized() -> bool:
    return _state().initialized


def rank() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.rank


def size() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.size


def local_rank() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.local_rank


def local_size() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.local_size


def cross_rank() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.cross_rank


def cross_size() -> int:
    state = _state()
    state.require_init()
    return state.rank_info.cross_size


def num_chips() -> int:
    """Total accelerator chips across the world (TPU-specific addition):
    size() counts processes; this counts devices."""
    import jax
    _state().require_init()
    return jax.device_count()


def local_chips() -> int:
    import jax
    _state().require_init()
    return jax.local_device_count()


def is_homogeneous() -> bool:
    state = _state()
    state.require_init()
    return state.is_homogeneous


def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    # The TCP control plane is the gloo analog and is always available.
    return True


def gloo_enabled() -> bool:
    return True


def nccl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def xla_enabled() -> bool:
    return True


def metrics_snapshot() -> dict:
    """Plain-dict snapshot of this process's runtime metrics registry:
    ``{"counters": {...}, "gauges": {...}, "histograms": {...}}``.
    Labeled metrics map ``"k=v,..."`` child keys to values; histograms
    carry count/sum/min/max plus fixed log-scale buckets.  Meaningful
    before/after init (the registry is process-wide); see
    docs/observability.md."""
    from . import metrics as metrics_mod
    return metrics_mod.snapshot()


def cluster_metrics_snapshot():
    """Merged cross-rank snapshot, available on the rank that hosts the
    Python coordinator once HOROVOD_METRICS_AGG_SECONDS-driven polls
    have collected per-rank snapshots; None anywhere else (workers,
    native coordinator, aggregation disabled).  With a relay tree
    armed (HOROVOD_COORD_FANOUT>0) the merge is O(fanout) at the root:
    relays pre-aggregate their subtree's replies into one MA frame
    each, and the returned ``ranks`` list still names every leaf
    contributor."""
    state = _state()
    server = getattr(getattr(state.runtime, "controller", None),
                     "server", None)
    if server is None or not hasattr(server, "merged_metrics"):
        return None
    return server.merged_metrics()


def status() -> dict:
    """The live job-health view (JSON-ready) served at ``GET /status``
    next to ``/metrics`` — the "which rank is slow RIGHT NOW" plane
    (docs/observability.md).

    Every rank reports its local view: replay + tune phase, queue
    depth, op rate, and its own phase-time EWMAs when the straggler
    observatory (``HOROVOD_STRAGGLER=1``) is armed.  The rank hosting
    the Python coordinator additionally embeds the ``cluster`` section:
    per-rank alive/limbo/wedged/lost liveness states, straggler scores
    and slow flags, and negotiation counters.  ``tools/hvdtop.py``
    renders this dict live."""
    from . import metrics as metrics_mod
    from . import profiler as profiler_mod
    from . import slo as slo_mod
    from . import straggler as straggler_mod
    state = _state()
    rt = state.runtime
    out = {
        "rank": state.rank_info.rank,
        "size": state.rank_info.size,
        "initialized": state.initialized,
        "straggler_armed": straggler_mod.ENABLED,
        "profile_armed": profiler_mod.ENABLED,
        "slo_armed": slo_mod.ENABLED,
    }
    snap = metrics_mod.snapshot()
    counters = snap.get("counters", {})

    def _total(name):
        v = counters.get(name, 0.0)
        return sum(v.values()) if isinstance(v, dict) else v

    replay = getattr(rt, "replay", None)
    out["replay"] = {
        "enabled": bool(state.knobs.replay_enabled),
        "active": bool(replay is not None and replay.active),
        "cycles_replayed": _total("hvd_steady_state_cycles_replayed"),
        "entries": _total("hvd_steady_state_entries"),
    }
    out["tune"] = tune_status()
    if rt is not None:
        out["queue_depth"] = rt.tensor_queue.outstanding()
    out["ops_dispatched"] = _total("hvd_responses_dispatched_total")
    collector = getattr(rt, "phase_collector", None)
    if straggler_mod.ENABLED and collector is not None:
        out["phases"] = collector.local_phases()
    if slo_mod.ENABLED:
        out["slo"] = slo_mod.slo_status()
    if profiler_mod.ENABLED:
        prof = profiler_mod.instance()
        if prof is not None:
            out["hot_frames"] = prof.top_frames()
    server = getattr(getattr(rt, "controller", None), "server", None)
    cluster = getattr(server, "status", None)
    if cluster is not None:
        out["cluster"] = cluster()
    return out


def slo_status() -> dict:
    """The SLO plane's live view (``hvd.slo_status()``): targets,
    short/long-window achieved SLIs, burn rates, and alert counts —
    ``{"enabled": False}`` when ``HOROVOD_SLO`` is off.  Callable
    before init (the plane arms at import)."""
    from . import slo as slo_mod
    return slo_mod.slo_status()


def tune_status() -> Optional[dict]:
    """The autotune-then-freeze lifecycle view (docs/autotune.md).

    On the rank hosting the tuning session (rank 0 with
    ``HOROVOD_TUNE=1``) this is the session's full status — phase
    (search/frozen/aborted), per-class sample counts and live/frozen
    knobs.  On every other rank it is the worker-side view: the
    currently applied worker knobs plus whether steady-state replay is
    being held for an active search.  None before init or when tuning
    was never enabled."""
    state = _state()
    sess = state.tune_session
    if sess is not None:
        return sess.status()
    rt = state.runtime
    if rt is None or not (state.knobs.tune or state.knobs.autotune
                          or state.knobs.tune_profile_loaded):
        return None
    # The runtime's own lifecycle bit, not the replay tracker's hold:
    # with replay disabled there is no tracker, but the search is
    # still live until the freeze/abort announcement lands.
    holding = bool(getattr(rt, "tuning_active", False))
    return {
        "phase": ("search" if holding else "frozen"),
        "worker": {
            "cycle_time_ms": state.knobs.cycle_time_ms,
            "coalesce": state.knobs.request_coalescing,
            "replay_warmup": state.knobs.replay_warmup_cycles,
        },
        "profile_loaded": state.knobs.tune_profile_loaded,
    }


def start_timeline(file_path: str, mark_cycles: bool = False):
    """Start timeline recording at runtime (reference:
    horovod_start_timeline, operations.cc:738-764)."""
    state = _state()
    state.require_init()
    from .timeline import Timeline
    if state.timeline is not None:
        state.timeline.close()
    state.timeline = Timeline(file_path, rank=state.rank_info.rank,
                              mark_cycles=mark_cycles)
    if state.runtime is not None:
        state.runtime.timeline = state.timeline


def stop_timeline():
    state = _state()
    state.require_init()
    if state.timeline is not None:
        state.timeline.close()
        state.timeline = None
    if state.runtime is not None:
        state.runtime.timeline = None


def add_process_set(ranks) -> ProcessSet:
    state = _state()
    ps = ranks if isinstance(ranks, ProcessSet) else ProcessSet(ranks)
    if getattr(ps, "process_set_id", -1) is not None and \
            ps.process_set_id >= 0:
        # Double registration would duplicate the registry entry and
        # desync it from the id sentinel; registered iff id >= 0.
        raise ValueError(
            "process set %r is already registered (id %d); call "
            "remove_process_set first to re-register" %
            (ps, ps.process_set_id))
    ps.process_set_id = state.next_process_set_id
    state.next_process_set_id += 1
    state.process_sets.append(ps)
    _invalidate_replay("process_set_change")
    return ps


def remove_process_set(ps: ProcessSet):
    state = _state()
    if ps in state.process_sets and ps.process_set_id != 0:
        state.process_sets.remove(ps)
        # Unregistered again: submit-time validation rejects it until
        # re-added (which assigns a FRESH id — ids are never reused).
        ps.process_set_id = -1
        _invalidate_replay("process_set_change")


def _invalidate_replay(reason: str):
    """Process-set membership changed: a frozen steady-state schedule
    may reference the old grouping — exit replay / reset convergence
    (collective call, so every rank invalidates at the same point)."""
    rt = _state().runtime
    if rt is not None and getattr(rt, "replay", None) is not None:
        rt.replay.note_disruption(reason)
