"""Exception hierarchy for horovod_tpu.

TPU-native analog of the reference's ``horovod/common/exceptions.py``
(reference: common/exceptions.py:18-31): ``HorovodInternalError`` signals a
failed collective (elastic recovery restores committed state), while
``HostsUpdatedInterrupt`` tells the elastic ``run_fn`` loop that membership
changed but state is still good.
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails.

    Under elastic training this triggers state restoration and
    re-rendezvous rather than a crash.
    """


class HostsUpdatedInterrupt(Exception):
    """Raised when the set of participating hosts changed.

    ``skip_sync`` is True when the update arrived from a graceful host
    addition: the current state is still consistent, so the retry loop may
    skip the state re-sync.
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodVersionMismatchError(ImportError):
    """Raised when mixing incompatible framework-binding versions."""


class NotInitializedError(RuntimeError):
    """An API that requires ``hvd.init()`` was called before init."""

    def __init__(self, what="Horovod-TPU"):
        super().__init__(
            f"{what} has not been initialized; call hvd.init() first.")


class TensorShapeMismatchError(ValueError):
    """Coordinator-detected mismatch of shapes between ranks."""


class TensorDtypeMismatchError(ValueError):
    """Coordinator-detected mismatch of dtypes between ranks."""


class DuplicateTensorNameError(ValueError):
    """A tensor name was submitted twice before the first completed.

    Mirrors the reference's DUPLICATE_NAME_ERROR (common.h:165-168).
    """


class StalledTensorError(RuntimeError):
    """One or more ranks failed to submit a tensor within the stall window."""
