"""Black-box flight recorder: bounded ring of typed control-plane events.

The forensic layer the live metrics registry (counters: *how many*) and
the per-rank Timeline (local spans: *how long*) cannot provide: when a
rank is promoted to lost, a relay dies mid-negotiation, or a stall
shutdown fires, the question is *which hop dropped frame N, what did
the leaf see, and where did the recovery time actually go* — evidence
that is gone by the time anyone looks unless it was being recorded all
along.  Following the PyTorch NCCL flight recorder and the Dapper
lineage (PAPERS.md), every process keeps a fixed-size in-memory ring
of typed events recorded from the hot paths:

  * frame send/recv on the coordinator, worker and relay links (kind,
    session, implicit stream ordinal, byte size, peer);
  * liveness traffic: HB heartbeats, suppression, silent-peer
    promotions;
  * the reconnecting channel: limbo entry, resume handshakes (WE),
    refusals, grace expiry;
  * relay attach / re-home hops / epoch bumps / subtree loss;
  * steady-state replay enter/exit with the exit reason;
  * checkpoint prepare/commit/restore phases;
  * elastic transitions (epoch plans, lost-rank evictions);
  * failpoint triggers (the chaos schedule, in causal position);
  * eager submissions (tensor name + type — the per-collective record
    the NCCL flight recorder keeps, feeding stall attribution).

Design constraints (this sits ON the frame and submit hot paths):

  * one attribute check when disabled — every site is written as

        if flight_recorder.ENABLED:
            flight_recorder.record(...)

    exactly the failpoints/liveness precedent, asserted by
    tests/test_flight_recorder.py;
  * bounded — a ``collections.deque(maxlen=N)`` ring: a week-long run
    holds the same memory as a one-minute run, eviction is O(1);
  * lock-light — an append is a tuple build + deque.append (atomic
    under the GIL); no lock is taken on the record path;
  * dependency-free — stdlib only, importable before anything else in
    the package.

Events carry BOTH clocks (``time.monotonic`` for intra-process
ordering, ``time.time`` for cross-rank merging) plus the identifiers
the control plane already has — session id, implicit frame ordinal,
connection generation/epoch — so the cross-rank merge needs NO wire
format change: ``tools/blackbox_merge.py`` aligns per-rank clocks from
HB round-trips and matches frames by (session, ordinal).

Dump triggers (per-rank JSON under ``HOROVOD_BLACKBOX_DIR``):
lost-rank promotion, stall shutdown, fatal unwind, SIGUSR2, chaos
drill end — plus an HMAC-guarded ``/blackbox`` handler next to the
Prometheus endpoint (common/metrics.py) for live extraction.

Enabling: set ``HOROVOD_BLACKBOX=1`` (ring only; dump via SIGUSR2 or
/blackbox) or ``HOROVOD_BLACKBOX_DIR=/path`` (ring + automatic dumps
on the triggers above).  The chaos/MTTR drills arm it themselves.
"""

import collections
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from . import env as _env

logger = logging.getLogger("horovod_tpu.blackbox")

ENV_ENABLE = "HOROVOD_BLACKBOX"
ENV_DIR = "HOROVOD_BLACKBOX_DIR"
ENV_CAPACITY = "HOROVOD_BLACKBOX_EVENTS"
DEFAULT_CAPACITY = 8192

# --- typed event kinds ----------------------------------------------------
# Wire plane
FRAME_TX = "frame_tx"        # kind, nbytes, seq?, peer?, sess?
FRAME_RX = "frame_rx"        # kind, nbytes, seq?, peer?, sess?
HB_TX = "hb_tx"              # role; a liveness heartbeat left this node
HB_RX = "hb_rx"              # peer; a heartbeat arrived
# Liveness / reconnect
PROMOTE = "promote"          # peer, clean, reason — rank promoted lost
LIMBO = "limbo"              # peer — link parked awaiting resume
RESUME = "resume"            # peer?, outcome, replayed?, sess?
REGISTER = "register"        # peer, sess, cycle — fresh link
WEDGE = "wedge"              # liveness-silent peer observed
# Relay tree
RELAY_ATTACH = "relay_attach"    # relay, depth, gen
RELAY_DOWN = "relay_down"        # relay, reason, subtree
RELAY_LOST = "relay_lost"        # relay, kind, ranks — RL notice
REHOME = "rehome"                # hop, outcome — leaf climbed its chain
# Replay
REPLAY = "replay"            # phase=enter/exit, reason?, batches?
# Autotune-then-freeze (horovod_tpu/tune): lifecycle transitions +
# knob proposals, so a postmortem shows WHICH phase the search was in
# (and which knobs were live) when a drill killed a rank mid-search.
TUNE = "tune"                # phase=search/propose/frozen/aborted
# Checkpoint
CKPT = "ckpt"                # phase, step, outcome?
# Online serving plane (horovod_tpu/serve): snapshot flips — every
# atomic swap of the served snapshot records WHICH committed step went
# live and how (bootstrap / incremental delta apply / full rebase), so
# a postmortem can line the read path's freshness up against the
# trainer's commit timeline.
SERVE = "serve"              # phase=flip, step, mode, tables?
# Elastic
ELASTIC = "elastic"          # event, epoch?, rank?
# Closed-loop elasticity (runner/elastic/policy.py): typed resize
# events, so a postmortem verdict can NAME the resize trigger
# (scale-up discovery / straggler migration / death) from the events
# alone — tools/blackbox_merge.py maps these to verdict triggers.
ELASTIC_SCALE_UP = "elastic_scale_up"  # hosts, slots, epoch, trigger
ELASTIC_MIGRATE = "elastic_migrate"    # rank, host?, score, phase
# Fault plane
FAILPOINT = "failpoint"      # site, action
FATAL = "fatal"              # error — this rank's world broke
STALL = "stall"              # tensor, missing — stall machinery fired
STRAGGLER = "straggler"      # peer, score — rank crossed the slow
                             # threshold (common/straggler.py)
SUBMIT = "submit"            # name, type — one eager collective
# Why-is-it-slow plane (common/profiler.py, common/slo.py): triggered
# profile captures carry the dominant frames at the moment a symptom
# (straggler flag / stall / SLO burn) fired; SLO_BURN marks the
# multi-window burn-rate crossing itself.
PROFILE = "profile"          # rank?, reason, detail?, frames
SLO_BURN = "slo_burn"        # sli, short, long, target — burn alert
NOTE = "note"                # harness / drill markers (drill.fault ...)

_VERSION = 1

# THE disabled-path gate: every site checks this one module attribute
# before anything else.  configure()/reset() are the only writers.
ENABLED = False

_lock = threading.Lock()          # guards configuration + dumps only
_ring: "collections.deque" = collections.deque(maxlen=DEFAULT_CAPACITY)
_capacity = DEFAULT_CAPACITY
_dir: Optional[str] = None
_rank: Optional[object] = None    # default tag for untagged events
_dump_counter = 0
_last_dump: Dict[str, float] = {}  # reason -> monotonic of last dump
_DUMP_THROTTLE_S = 2.0
_sigusr2_installed = False


def configure(directory: Optional[str] = None,
              capacity: Optional[int] = None,
              enabled: bool = True):
    """(Re)arm the recorder.  ``directory`` enables automatic dumps on
    the failure triggers; without it the ring still records and can be
    extracted via SIGUSR2 (cwd), /blackbox, or an explicit dump()."""
    global ENABLED, _ring, _capacity, _dir
    with _lock:
        if capacity is not None and capacity != _capacity:
            _capacity = max(16, int(capacity))
            _ring = collections.deque(_ring, maxlen=_capacity)
        if directory is not None:
            _dir = directory or None
        ENABLED = bool(enabled)
    if enabled:
        logger.debug("flight recorder armed (capacity=%d, dir=%s)",
                     _capacity, _dir)


def reset():
    """Disable and drop all events (tests/drill teardown)."""
    global ENABLED, _ring, _dir, _rank
    with _lock:
        ENABLED = False
        _ring = collections.deque(maxlen=_capacity)
        _dir = None
        _rank = None
        _last_dump.clear()


def set_rank(rank):
    """Default rank tag for events recorded without an explicit one
    (wired from hvd.init, the failpoints.set_rank precedent)."""
    global _rank
    _rank = rank


def record(kind: str, rank=None, **fields):
    """Append one typed event.  Callers gate on ``ENABLED`` first so
    the disabled cost is one attribute check; the enabled cost is a
    tuple build + deque.append (no lock, bounded ring)."""
    _ring.append((time.monotonic(), time.time(),
                  kind, _rank if rank is None else rank, fields))


def note(kind: str, mono: Optional[float] = None,
         wall: Optional[float] = None, **fields):
    """Harness-level marker (drill fault fired, first post-restore
    step...).  ``mono``/``wall`` override the stamp so a harness can
    record an instant it measured earlier at its true position.
    Gated like record(): a disarmed recorder takes no markers — a
    stale ``drill.fault`` surviving into a later armed session would
    anchor an unrelated postmortem's span breakdown."""
    if not ENABLED:
        return
    now_m, now_w = time.monotonic(), time.time()
    m = now_m if mono is None else mono
    # Keep the two clocks consistent when only mono is overridden.
    w = wall if wall is not None else now_w - (now_m - m)
    _ring.append((m, w, NOTE, "harness", dict(fields, note=kind)))


def events(rank=None) -> List[tuple]:
    """Snapshot of the ring (oldest first), optionally filtered by
    rank tag."""
    snap = list(_ring)
    if rank is None:
        return snap
    return [e for e in snap if e[3] == rank]


def recent_for_tensors(names, n: int = 8) -> List[dict]:
    """The last ``n`` events mentioning any of ``names`` (stall
    attribution: a warning names WHAT the implicated tensors last did,
    not just which ranks are missing)."""
    wanted = set(names)
    out = []
    for ev in reversed(list(_ring)):
        f = ev[4]
        if f.get("name") in wanted or f.get("tensor") in wanted:
            out.append(_event_dict(ev))
            if len(out) >= n:
                break
    out.reverse()
    return out


def _event_dict(ev: tuple) -> dict:
    mono, wall, kind, rank, fields = ev
    # Reserved keys win: a payload field named "kind"/"rank" (e.g. a
    # wire-frame kind — call sites use "frame" for that) must never
    # clobber the event's own type or origin in the dump.
    d = dict(fields)
    d.update({"mono": mono, "wall": wall, "kind": kind, "rank": rank})
    return d


def _rank_tags(snap) -> List[object]:
    tags = []
    for ev in snap:
        if ev[3] not in tags:
            tags.append(ev[3])
    return tags


def dump_dict(rank=None, reason: str = "manual",
              snap: Optional[List[tuple]] = None) -> dict:
    """One rank's dump as a JSON-ready dict — THE dump schema, shared
    by the per-file writer below and the /blackbox HTTP payload so the
    two can never drift.  ``snap`` lets dump() reuse one ring snapshot
    across every rank tag's file."""
    if snap is None:
        snap = events(rank)
    return {
        "version": _VERSION,
        "reason": reason,
        "rank": rank if rank is not None else _rank,
        "pid": os.getpid(),
        "mono_at_dump": time.monotonic(),
        "wall_at_dump": time.time(),
        "events": [_event_dict(e) for e in snap],
    }


def dump(reason: str, directory: Optional[str] = None,
         throttle: bool = False) -> List[str]:
    """Write per-rank JSON dumps and return the paths.  One file per
    distinct rank tag in the ring: a real multi-process job holds only
    its own rank's events; the in-process chaos harness holds every
    thread-rank's, and each gets its own file so the merge sees the
    same shape either way.  ``throttle`` limits repeat dumps for one
    reason (promotion storms) to one per few seconds."""
    global _dump_counter
    with _lock:
        target = directory or _dir
        if not target:
            return []
        now = time.monotonic()
        if throttle and now - _last_dump.get(reason, -1e9) < \
                _DUMP_THROTTLE_S:
            return []
        _last_dump[reason] = now
        _dump_counter += 1
        serial = _dump_counter
        snap = list(_ring)
    paths = []
    try:
        os.makedirs(target, exist_ok=True)
        for tag in _rank_tags(snap):
            body = dump_dict(rank=tag, reason=reason,
                             snap=[e for e in snap if e[3] == tag])
            path = os.path.join(
                target, "blackbox-rank%s-%s-%d.json"
                % (tag, reason.replace("/", "_"), serial))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)
            paths.append(path)
    except OSError:
        logger.warning("flight-recorder dump to %s failed", target,
                       exc_info=True)
    if paths:
        logger.info("flight recorder dumped %d file(s) to %s (%s)",
                    len(paths), target, reason)
    return paths


def trigger_dump(reason: str):
    """Failure-path hook (promotion, stall shutdown, fatal unwind):
    dump if a directory is configured, never raise, throttle storms."""
    try:
        dump(reason, throttle=True)
    except Exception:
        logger.warning("flight-recorder trigger %s failed", reason,
                       exc_info=True)


def install_signal_handler():
    """SIGUSR2 → dump (the classic black-box extraction signal).  Only
    possible from the main thread; callers on other threads get a
    debug log, not an error."""
    global _sigusr2_installed
    if _sigusr2_installed:
        return True
    try:
        import signal

        def _handler(signum, frame):
            # NEVER dump inline: the handler runs on the main thread
            # between bytecodes, and dump() takes the non-reentrant
            # module lock — a signal landing while the main thread
            # itself holds it (fatal-path trigger_dump, back-to-back
            # SIGUSR2) would deadlock the process.  A short-lived
            # thread acquires the lock like any other caller.
            threading.Thread(target=trigger_dump, args=("sigusr2",),
                             name="hvd-blackbox-sigusr2",
                             daemon=True).start()

        signal.signal(signal.SIGUSR2, _handler)
        _sigusr2_installed = True
        return True
    except (ValueError, OSError, AttributeError):
        # Non-main thread, or a platform without SIGUSR2.
        logger.debug("SIGUSR2 dump handler not installed",
                     exc_info=True)
        return False


# Arm from the environment at import: the knobs ride the launcher env
# contract to every worker, so one setting on the driver arms the job
# (the HOROVOD_FAILPOINTS precedent).
_env_dir = _env.env_str_opt(ENV_DIR)
_env_on = _env.env_bool(ENV_ENABLE)
if _env_dir or _env_on:
    _cap = _env.env_int(ENV_CAPACITY, DEFAULT_CAPACITY)
    configure(directory=_env_dir, capacity=_cap, enabled=True)
