"""Runtime metrics: process-wide counters, gauges, and histograms.

The live-numbers layer the Timeline (post-hoc chrome trace) and the
StallInspector (log lines) cannot provide: every hot path — the
background cycle loop, the controller frame plane, fusion planning, the
response cache, and the collective backends — accumulates into one
process-wide registry that can be read at any moment.

Design constraints (this sits ON the hot paths):

  * lock-cheap: one small lock per metric; an increment is a dict get +
    float add.  No allocation on the steady-state path.
  * bounded: histograms accumulate into FIXED log-scale buckets (no
    per-sample storage) — a week-long run holds the same few hundred
    floats as a one-minute run.
  * dependency-free: stdlib only; importable before jax, safe from any
    thread, meaningful before/after ``hvd.init()``.

Three read paths:

  * ``snapshot()`` → plain nested dict (the ``hvd.metrics_snapshot()``
    API, also what bench.py embeds in BENCH artifacts);
  * ``render_snapshot()`` / ``MetricsRegistry.render_prometheus()`` →
    Prometheus text exposition, served by :class:`MetricsServer` when
    ``HOROVOD_METRICS_PORT`` is set (guarded by the same job-secret
    HMAC as the rendezvous KV server);
  * ``merge_snapshots()`` → cross-rank aggregation: the rank-0
    coordinator collects per-rank snapshots over the control plane
    (controller_net MQ/MR frames) and exposes the merged view.
"""

import bisect
import functools
import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger("horovod_tpu.metrics")


def log_bounds(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` log-scale bucket upper bounds from ``start`` by
    ``factor`` — the fixed-size accumulation grid for histograms."""
    out: List[float] = []
    b = float(start)
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


# Default grids.  Times span 1 µs (an inline cache-hit send) to ~67 s
# (a stalled negotiation); bytes span one cache-line-ish payload to
# ~17 GB; counts cover fusion batch sizes.
TIME_BUCKETS = log_bounds(1e-6, 2.0, 27)
BYTE_BUCKETS = log_bounds(256.0, 4.0, 14)
COUNT_BUCKETS = log_bounds(1.0, 2.0, 16)


def _sanitize(value: object) -> str:
    """Label values may carry wire-derived bytes (e.g. frame magics):
    strip the structural characters of the canonical key AND anything
    non-printable, so a hostile or corrupt value can never forge extra
    labels or emit exposition-breaking bytes (a raw newline in a label
    would make every subsequent scrape unparseable)."""
    return "".join(ch if 32 <= ord(ch) < 127 and ch not in ',="'
                   else "_" for ch in str(value))


def _label_key(labels: Dict[str, object]) -> str:
    """Canonical label serialization (sorted ``k=v`` pairs): the child
    key in snapshots and the inside of the Prometheus ``{...}``."""
    return ",".join("%s=%s" % (k, _sanitize(labels[k]))
                    for k in sorted(labels))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[str, object] = {}

    def clear(self):
        """Zero the metric in place (tests).  The object itself stays
        registered — instrumented modules hold references to it."""
        with self._lock:
            self._children.clear()

    def drop(self, **labels):
        """Retire every labeled child matching ALL given label values.
        Publishers that re-emit a bounded top-K family (the profiler
        digest) use this so stale label combinations don't outlive the
        set they belonged to — a labeled child otherwise lives forever."""
        match = set("%s=%s" % (k, _sanitize(labels[k])) for k in labels)
        with self._lock:
            for key in [k for k in self._children
                        if match.issubset(k.split(","))]:
                del self._children[key]

    def _collapse(self, d: dict):
        """Unlabeled metrics snapshot to a bare value; labeled ones to
        ``{label_key: value}``."""
        if list(d.keys()) == [""]:
            return d[""]
        return d


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        key = _label_key(labels) if labels else ""
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def value(self, **labels) -> float:
        key = _label_key(labels) if labels else ""
        with self._lock:
            return float(self._children.get(key, 0.0))

    def snapshot(self):
        with self._lock:
            return self._collapse(dict(self._children))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        key = _label_key(labels) if labels else ""
        with self._lock:
            self._children[key] = float(value)

    def inc(self, value: float = 1.0, **labels):
        key = _label_key(labels) if labels else ""
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        key = _label_key(labels) if labels else ""
        with self._lock:
            return float(self._children.get(key, 0.0))

    snapshot = Counter.snapshot


class Histogram(_Metric):
    """Fixed log-scale-bucket histogram: ``observe()`` is a bisect over
    ~two dozen bounds plus a few float adds — cheap enough for per-call
    ``time.perf_counter`` deltas on the cycle loop."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 bounds: Tuple[float, ...] = TIME_BUCKETS):
        super().__init__(name, help)
        self.bounds = tuple(bounds)

    def observe(self, value: float, **labels):
        value = float(value)
        # Slot i counts values <= bounds[i]; the final slot is +Inf.
        idx = bisect.bisect_left(self.bounds, value)
        key = _label_key(labels) if labels else ""
        with self._lock:
            h = self._children.get(key)
            if h is None:
                h = {"counts": [0] * (len(self.bounds) + 1),
                     "sum": 0.0, "count": 0, "min": None, "max": None}
                self._children[key] = h
            h["counts"][idx] += 1
            h["sum"] += value
            h["count"] += 1
            if h["min"] is None or value < h["min"]:
                h["min"] = value
            if h["max"] is None or value > h["max"]:
                h["max"] = value

    def _child_snapshot(self, h: dict) -> dict:
        buckets = [[le, c] for le, c in zip(self.bounds, h["counts"])]
        buckets.append(["+Inf", h["counts"][-1]])
        return {"count": h["count"], "sum": h["sum"],
                "min": h["min"], "max": h["max"], "buckets": buckets}

    def snapshot(self):
        with self._lock:
            return self._collapse({k: self._child_snapshot(h)
                                   for k, h in self._children.items()})


class MetricsRegistry:
    """Name → metric map with get-or-create semantics: any module may
    declare the same metric; the first declaration wins (a kind clash
    is a programming error and raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "OrderedDict[str, _Metric]" = OrderedDict()

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    "metric %r already registered as %s, not %s"
                    % (name, m.kind, cls.kind))
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  bounds: Tuple[float, ...] = TIME_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, bounds=bounds)

    def reset(self):
        """Zero every metric in place (see _Metric.clear)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()

    def snapshot(self) -> dict:
        """Plain nested dict, JSON-serializable: the wire format for
        cross-rank aggregation and the ``hvd.metrics_snapshot()``
        return value."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            snap = m.snapshot()
            if snap == {} or snap is None:
                continue
            out[m.kind + "s"][m.name] = snap
        return out

    def render_prometheus(self) -> str:
        with self._lock:
            helps = {m.name: m.help for m in self._metrics.values()}
            kinds = {m.name: m.kind for m in self._metrics.values()}
        snap = self.snapshot()
        # Emit TYPE headers even for still-empty metrics so a scrape of
        # a fresh process is non-empty and self-describing.
        empties = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, kind in kinds.items():
            section = kind + "s"
            if name not in snap.get(section, {}):
                empties[section][name] = None
        text = render_snapshot(snap, helps=helps)
        for section in ("counters", "gauges", "histograms"):
            for name in empties[section]:
                text += "# TYPE %s %s\n" % (name, section[:-1])
        return text


def _prom_escape(v: object) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"')


def _prom_labels(key: str, extra: str = "") -> str:
    parts = []
    if key:
        for item in key.split(","):
            k, _, v = item.partition("=")
            parts.append('%s="%s"' % (k, _prom_escape(v)))
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _as_children(val) -> Dict[str, object]:
    """Normalize a snapshot entry to {label_key: value} form (bare
    values and unlabeled histogram children collapse to key "")."""
    if isinstance(val, dict) and not ("count" in val and "buckets" in val):
        return val
    return {"": val}


def render_snapshot(snap: dict, prefix: str = "",
                    helps: Optional[Dict[str, str]] = None) -> str:
    """Prometheus text exposition of a snapshot dict.  ``prefix`` lets
    an aggregated (cluster-merged) snapshot render next to the local
    one without name collisions."""
    helps = helps or {}
    lines: List[str] = []
    for section, ptype in (("counters", "counter"), ("gauges", "gauge")):
        for name, val in sorted(snap.get(section, {}).items()):
            full = prefix + name
            if helps.get(name):
                lines.append("# HELP %s %s" % (full, helps[name]))
            lines.append("# TYPE %s %s" % (full, ptype))
            for key, v in sorted(_as_children(val).items()):
                lines.append("%s%s %s" % (full, _prom_labels(key), v))
    for name, val in sorted(snap.get("histograms", {}).items()):
        full = prefix + name
        if helps.get(name):
            lines.append("# HELP %s %s" % (full, helps[name]))
        lines.append("# TYPE %s histogram" % full)
        for key, h in sorted(_as_children(val).items()):
            cum = 0
            for le, c in h.get("buckets", []):
                cum += c
                le_s = "+Inf" if le == "+Inf" else repr(float(le))
                lines.append("%s_bucket%s %d" % (
                    full, _prom_labels(key, 'le="%s"' % le_s), cum))
            lines.append("%s_sum%s %s" % (full, _prom_labels(key),
                                          h.get("sum", 0.0)))
            lines.append("%s_count%s %d" % (full, _prom_labels(key),
                                            h.get("count", 0)))
    return "\n".join(lines) + "\n" if lines else ""


def _merge_hist(a: dict, b: dict) -> dict:
    out = {"count": a.get("count", 0) + b.get("count", 0),
           "sum": a.get("sum", 0.0) + b.get("sum", 0.0)}
    mins = [m for m in (a.get("min"), b.get("min")) if m is not None]
    maxs = [m for m in (a.get("max"), b.get("max")) if m is not None]
    out["min"] = min(mins) if mins else None
    out["max"] = max(maxs) if maxs else None
    ab, bb = a.get("buckets", []), b.get("buckets", [])
    if len(ab) == len(bb) and all(x[0] == y[0] for x, y in zip(ab, bb)):
        out["buckets"] = [[x[0], x[1] + y[1]] for x, y in zip(ab, bb)]
    else:  # mismatched grids (mixed versions): keep totals only
        out["buckets"] = []
    return out


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Element-wise sum of snapshot dicts: counters and gauges add
    (gauges therefore read as cross-rank totals, e.g. total outstanding
    tensors), histograms merge bucket-wise."""
    merged = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for section in ("counters", "gauges"):
            for name, val in snap.get(section, {}).items():
                acc = merged[section].setdefault(name, {})
                for key, v in _as_children(val).items():
                    acc[key] = acc.get(key, 0.0) + v
        for name, val in snap.get("histograms", {}).items():
            acc = merged["histograms"].setdefault(name, {})
            for key, h in _as_children(val).items():
                acc[key] = _merge_hist(acc[key], h) if key in acc else h
    for section in merged:
        merged[section] = {
            name: (children[""] if list(children.keys()) == [""]
                   else children)
            for name, children in merged[section].items()}
    return merged


# ---------------------------------------------------------------------------
# The process-wide registry + module-level conveniences
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              bounds: Tuple[float, ...] = TIME_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, bounds=bounds)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset():
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# Collective instrumentation shared by the data-plane backends
# ---------------------------------------------------------------------------

COLLECTIVE_OPS = REGISTRY.counter(
    "hvd_collective_ops_total",
    "Collective dispatches by data-plane backend and op type")
COLLECTIVE_BYTES = REGISTRY.counter(
    "hvd_collective_bytes_total",
    "Payload bytes moved per backend and op type")
COLLECTIVE_SECONDS = REGISTRY.histogram(
    "hvd_collective_seconds",
    "Host wall time per collective dispatch (includes device wait only "
    "when the caller blocks)", bounds=TIME_BUCKETS)


def list_nbytes(arrays, *args, **kwargs) -> int:
    """Payload bytes of a tensor batch without forcing a device
    transfer (jax and numpy arrays both expose .nbytes)."""
    return sum(int(getattr(a, "nbytes", 0)) for a in arrays)


def one_nbytes(array, *args, **kwargs) -> int:
    return int(getattr(array, "nbytes", 0))


def record_collective(backend: str, op: str, nbytes: int, seconds: float):
    COLLECTIVE_OPS.inc(1, backend=backend, op=op)
    COLLECTIVE_BYTES.inc(nbytes, backend=backend, op=op)
    COLLECTIVE_SECONDS.observe(seconds, backend=backend, op=op)


def timed_collective(backend: str, op: str,
                     nbytes_fn: Callable[..., int]):
    """Method decorator for backend collectives: times the call and
    records op count + payload bytes.  ``nbytes_fn`` receives the
    method's arguments (minus self) and must be side-effect free."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            t0 = time.perf_counter()
            result = fn(self, *args, **kwargs)
            dt = time.perf_counter() - t0
            try:
                record_collective(backend, op,
                                  int(nbytes_fn(*args, **kwargs)), dt)
            except Exception:
                logger.debug("collective metrics failed", exc_info=True)
            return result
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# /metrics HTTP endpoint (opt-in via HOROVOD_METRICS_PORT)
# ---------------------------------------------------------------------------

class MetricsServer:
    """Threaded Prometheus-text endpoint reusing the rendezvous KV
    server's handler plumbing — including its job-secret HMAC guard, so
    the endpoint is never an unauthenticated sidechannel when the job
    runs with a secret (launchers always set one; direct/unit-test use
    without ``HOROVOD_SECRET_KEY`` serves openly, matching
    RendezvousServer semantics)."""

    def __init__(self, port: int = 0, registry: Optional[MetricsRegistry] = None,
                 cluster_provider: Optional[Callable[[], Optional[dict]]] = None,
                 secret: Optional[str] = None,
                 status_provider: Optional[Callable[[], Optional[dict]]] = None,
                 profile_provider: Optional[Callable[[], Optional[dict]]] = None):
        from http.server import ThreadingHTTPServer

        from ..runner import job_secret
        from ..runner.http_server import (NOT_FOUND, OK, KVStoreHandler,
                                          ReplayCache)

        self._registry = registry if registry is not None else REGISTRY
        self._cluster_provider = cluster_provider
        self._status_provider = status_provider
        self._profile_provider = profile_provider
        server_self = self

        class _MetricsHandler(KVStoreHandler):
            def do_GET(self):
                if not self._authorized():
                    return
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/blackbox":
                    # Live black-box extraction: the flight recorder's
                    # ring as JSON, behind the SAME job-secret HMAC as
                    # /metrics (a postmortem dump is a traffic log —
                    # never an unauthenticated sidechannel).
                    from . import flight_recorder
                    body = json.dumps(flight_recorder.dump_dict(
                        reason="http")).encode()
                    self.send_response(OK)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/status":
                    # Live cluster/status view (common/straggler.py +
                    # hvd.status()): per-rank alive/limbo/wedged/slow,
                    # replay + tune phase, queue depth, straggler
                    # scores — behind the SAME job-secret HMAC as
                    # /metrics (a liveness map is a topology map,
                    # never an unauthenticated sidechannel).  404
                    # when no provider is wired (bare registry
                    # servers).
                    provider = server_self._status_provider
                    if provider is None:
                        self.send_response(NOT_FOUND)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    try:
                        payload = provider()
                    except Exception:
                        logger.debug("status provider failed",
                                     exc_info=True)
                        payload = None
                    body = json.dumps(
                        payload if payload is not None else {}
                    ).encode()
                    self.send_response(OK)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/profile":
                    # This rank's sampling-profiler payload
                    # (common/profiler.py): flame-ready collapsed
                    # stacks + lane/GIL/blocking shares + the last
                    # triggered capture — behind the SAME job-secret
                    # HMAC as /metrics (a live stack profile is a
                    # code map, never an unauthenticated
                    # sidechannel).  404 when no provider is wired
                    # (bare registry servers).
                    provider = server_self._profile_provider
                    if provider is None:
                        self.send_response(NOT_FOUND)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    try:
                        payload = provider()
                    except Exception:
                        logger.debug("profile provider failed",
                                     exc_info=True)
                        payload = None
                    body = json.dumps(
                        payload if payload is not None else {}
                    ).encode()
                    self.send_response(OK)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path != "/metrics":
                    self.send_response(NOT_FOUND)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = server_self.render().encode()
                self.send_response(OK)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                self._reject(405)

            def do_DELETE(self):
                self._reject(405)

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                          _MetricsHandler)
        self._httpd.kvstore = None
        self._httpd.secret = secret if secret is not None \
            else job_secret.current()
        self._httpd.replay_cache = ReplayCache()
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-metrics-http",
            daemon=True)
        self._thread.start()
        logger.debug("metrics endpoint listening on %d", self.port)

    def render(self) -> str:
        text = self._registry.render_prometheus()
        if self._cluster_provider is not None:
            try:
                merged = self._cluster_provider()
            except Exception:
                logger.debug("cluster metrics provider failed",
                             exc_info=True)
                merged = None
            if merged:
                text += render_snapshot(merged, prefix="cluster_")
        return text

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def serve(port: int = 0, registry: Optional[MetricsRegistry] = None,
          cluster_provider=None, secret: Optional[str] = None,
          status_provider=None, profile_provider=None) -> MetricsServer:
    return MetricsServer(port=port, registry=registry,
                         cluster_provider=cluster_provider, secret=secret,
                         status_provider=status_provider,
                         profile_provider=profile_provider)
