"""Live straggler observatory: per-cycle critical-path attribution.

The observability stack answers "what happened" after the fact (metrics
registry, flight recorder, blackbox postmortems); this module answers
"which rank is slow *right now*" — the live signal ROADMAP item 5
needs to distinguish dead from merely-slow ranks and pre-emptively
migrate stragglers before the stall clock fires.  Dapper's contract
(PAPERS.md) is the shape: always-on attribution riding identifiers the
control plane already carries, analysis out-of-band.

Two attribution sources, because steady-state replay goes wire-silent
(the Li et al. VLDB '20 static-graph lesson — the one place the
coordinator could see per-rank readiness goes dark exactly when
production jobs spend their time):

* **Negotiation source** (coordinator side): every CH/RQ contribution
  already arrives in order at rank 0 — today that order is discarded.
  The scorer records per tensor which rank's readiness arrived last
  (``hvd_critical_path_total{rank}``), the ready-spread
  (``hvd_ready_spread_seconds``), and folds each rank's arrival lag
  (t_rank − t_first) into a per-rank EWMA.

* **Replay source** (worker side): each rank summarizes its own phase
  timings (submit→executed e2e, the fused→executed execute slice) into
  rank-labeled gauges (``hvd_worker_phase_seconds{rank,phase}``) that
  ride the EXISTING periodic MR metrics frames — zero new wire kinds,
  zero extra frames, and relay MR→MA pre-aggregation preserves them
  intact because per-rank labels survive ``metrics.merge_snapshots``
  (each rank only ever writes its own label).  The scorer inverts the
  classic straggler signature: a rank whose end-to-end collective
  latency sits far BELOW the cross-rank median is the rank everyone
  else spent that gap waiting on.

Scores are normalized lag ratios: ``lag / max(median_lag, floor)`` for
the negotiation source, ``(median_e2e − e2e) / max(e2e, floor)`` for
the wait-inversion source (floor = ``HOROVOD_STRAGGLER_MIN_LAG``, so
microsecond jitter in a tight world reads all-zero), combined by
elementwise max into ``hvd_straggler_score{rank}``.  Crossing
``HOROVOD_STRAGGLER_THRESHOLD`` emits one flight-recorder event and
publishes ``elastic/slow/<rank>`` to the rendezvous KV — the
consumable hook for verdict-driven pre-emptive migration (wired, not
yet acted on).  Hysteresis (re-arm below threshold/2) keeps a rank
oscillating around the line from storming the KV.

Design constraints (call sites live ON the submit/frame hot paths):

  * one module-attribute check when disabled — every site is written

        if straggler.ENABLED:
            straggler.note_latency(...)

    exactly the failpoints/flight-recorder precedent, asserted by
    tests/test_straggler.py and policed by the hvdlint hot-path gate;
  * lock-free note paths — worker EWMAs are plain float updates
    (atomic enough under the GIL; a lost sample is noise, not a bug);
  * bounded — pending arrival maps are per-in-flight-tensor and
    drained on completion/stall/elastic break; EWMAs are O(world).
"""

import logging
import statistics
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import env as _env
from . import flight_recorder as _fr
from . import metrics
from . import profiler as _prof

logger = logging.getLogger("horovod_tpu.straggler")

# THE disabled-path gate: every hot-path site checks this one module
# attribute before anything else.  configure()/reset() are the only
# writers (the failpoints/flight_recorder precedent).
ENABLED = False

_EWMA_ALPHA = 0.2
# Heartbeat period for the slow-rank hook while a rank STAYS flagged
# (crossing fires immediately; see refresh()).  Consumers treat a
# notice older than a few periods as "recovered".
_SLOW_REPUBLISH_S = 2.0

_SCORE = metrics.gauge(
    "hvd_straggler_score",
    "Normalized per-rank straggler score (lag relative to the "
    "cross-rank median; >= HOROVOD_STRAGGLER_THRESHOLD flags the rank "
    "slow)")
_CRITICAL_PATH = metrics.counter(
    "hvd_critical_path_total",
    "Negotiated tensors whose readiness this rank completed LAST "
    "(the per-cycle critical path, by rank)")
_READY_SPREAD = metrics.histogram(
    "hvd_ready_spread_seconds",
    "Per-tensor readiness spread at the coordinator "
    "(last arrival - first arrival)")
_FLAGS = metrics.counter(
    "hvd_straggler_flags_total",
    "Threshold crossings: a rank newly flagged slow, by rank")
_PHASES = metrics.gauge(
    "hvd_worker_phase_seconds",
    "Per-rank phase-time EWMAs published into MR metrics frames "
    "(phase: e2e = submit->executed, execute = fused->executed, "
    "negotiate = the difference)")
_OP_RATE = metrics.gauge(
    "hvd_worker_op_rate",
    "Per-rank completed collective ops per second (negotiated + "
    "replayed, counted at the completion callback; published at "
    "MR-poll cadence)")

def configure(enabled: bool = True):
    """(Re)arm the observatory.  Thresholds/floors are read freshly
    from the env by each scorer (the drills sweep them per phase)."""
    global ENABLED
    ENABLED = bool(enabled)
    if enabled:
        logger.debug("straggler observatory armed (threshold=%.2f, "
                     "min_lag=%.3fs)", _env.straggler_threshold(),
                     _env.straggler_min_lag())


def reset():
    """Disable the observatory (tests/drills)."""
    global ENABLED
    ENABLED = False


class PhaseCollector:
    """Per-runtime phase-time EWMAs (one per BackgroundRuntime, NOT
    module state — the in-process chaos harness runs every thread-rank
    in one interpreter, and a shared collector would blend the very
    per-rank signal attribution needs).

    note_* runs on the submit/dispatch hot paths — plain float
    updates, no lock (a lost sample under a race is noise); publish()
    runs on the cold MR-reply path."""

    __slots__ = ("e2e_ewma", "exec_ewma", "ops", "_rate_prev_ops",
                 "_rate_prev_t")

    def __init__(self):
        self.e2e_ewma: Optional[float] = None
        self.exec_ewma: Optional[float] = None
        # Completed ops THIS collector saw (negotiated + replayed —
        # the latency wrapper fires for both).  Counted here, not read
        # from the process registry: in the in-process harness every
        # thread-rank shares one registry, and a global count would
        # publish the same whole-world rate under every rank's label.
        self.ops = 0
        self._rate_prev_ops = 0
        self._rate_prev_t: Optional[float] = None

    def note_latency(self, seconds: float):
        """One submit→executed end-to-end sample (from the completion
        callback wrapper; gate on ENABLED at the call site)."""
        self.ops += 1
        prev = self.e2e_ewma
        self.e2e_ewma = seconds if prev is None else \
            prev + _EWMA_ALPHA * (seconds - prev)

    def note_exec(self, seconds: float):
        """One fused→executed (backend execution) sample."""
        prev = self.exec_ewma
        self.exec_ewma = seconds if prev is None else \
            prev + _EWMA_ALPHA * (seconds - prev)

    def publish(self, rank: int):
        """Fold the phase EWMAs + op rate into rank-labeled gauges so
        the NEXT MR reply carries them (cold, seconds cadence).  Each
        rank only ever writes its OWN label, which is what lets relay
        MA pre-aggregation (a snapshot sum) carry every rank's summary
        through intact."""
        e2e, exc = self.e2e_ewma, self.exec_ewma
        if e2e is not None:
            _PHASES.set(round(e2e, 6), rank=rank, phase="e2e")
            if exc is not None:
                _PHASES.set(round(max(0.0, e2e - exc), 6), rank=rank,
                            phase="negotiate")
        if exc is not None:
            _PHASES.set(round(exc, 6), rank=rank, phase="execute")
        now = time.monotonic()
        ops = self.ops
        if self._rate_prev_t is not None and now > self._rate_prev_t:
            rate = max(0, ops - self._rate_prev_ops) / \
                (now - self._rate_prev_t)
            _OP_RATE.set(round(rate, 3), rank=rank)
        self._rate_prev_ops, self._rate_prev_t = ops, now

    def local_phases(self) -> Dict[str, float]:
        """Current phase EWMAs (the hvd.status() local view); empty
        before any sample."""
        out: Dict[str, float] = {}
        if self.e2e_ewma is not None:
            out["e2e"] = round(self.e2e_ewma, 6)
        if self.exec_ewma is not None:
            out["execute"] = round(self.exec_ewma, 6)
            if self.e2e_ewma is not None:
                out["negotiate"] = round(
                    max(0.0, self.e2e_ewma - self.exec_ewma), 6)
        return out


def phases_from_snapshot(snap: dict) -> Dict[int, Dict[str, float]]:
    """Extract ``{rank: {phase: seconds}}`` from a metrics snapshot
    (an MR reply, a relay MA aggregate, or the merged cluster view) —
    the inverse of publish()'s rank-labeled gauges."""
    out: Dict[int, Dict[str, float]] = {}
    gauges = snap.get("gauges", {}) if isinstance(snap, dict) else {}
    children = gauges.get("hvd_worker_phase_seconds")
    if not isinstance(children, dict):
        return out
    for key, value in children.items():
        labels = dict(item.split("=", 1)
                      for item in key.split(",") if "=" in item)
        try:
            rank = int(labels["rank"])
            phase = labels["phase"]
            out.setdefault(rank, {})[phase] = float(value)
        except (KeyError, ValueError, TypeError):
            continue
    return out


# --- coordinator-side scorer ----------------------------------------------

class StragglerScorer:
    """Rank-0 scorer: folds negotiation arrival order and MR-carried
    worker phase summaries into normalized per-rank scores.

    note_arrival/note_complete are called under the coordinator's
    server lock (frame dispatch); refresh() runs on the coordinator's
    straggler loop.  Lock order is always server lock → scorer lock
    (never the reverse), so the lock witness sees no cycle."""

    def __init__(self, size: int,
                 on_slow: Optional[Callable[[int, float], None]] = None,
                 threshold: Optional[float] = None,
                 min_lag_s: Optional[float] = None,
                 alpha: float = _EWMA_ALPHA):
        self.size = size
        self.threshold = float(threshold) if threshold is not None \
            else _env.straggler_threshold()
        self.min_lag_s = float(min_lag_s) if min_lag_s is not None \
            else _env.straggler_min_lag()
        self._alpha = alpha
        self._on_slow = on_slow
        self._lock = threading.Lock()
        # (psid, name) -> (t_first, {rank: t_arrival}) for tensors
        # whose negotiation is in flight; drained on completion.
        self._pending: Dict[tuple, Tuple[float, Dict[int, float]]] = {}
        self._lag: Dict[int, float] = {}      # negotiation lag EWMAs
        self._wait: Dict[int, float] = {}     # MR-carried e2e EWMAs
        self._scores: Dict[int, float] = {}
        self._flagged: set = set()
        self._neg_samples = 0
        self._last_neg_t: Optional[float] = None
        self._last_refresh_t: Optional[float] = None
        self._last_slow_pub: Dict[int, float] = {}  # rank -> last hook t

    # -- feeding (coordinator frame dispatch, under the server lock) --
    def note_arrival(self, key: tuple, rank: int, t: float):
        with self._lock:
            ent = self._pending.get(key)
            if ent is None:
                self._pending[key] = (t, {rank: t})
            else:
                ent[1].setdefault(rank, t)

    def note_complete(self, key: tuple):
        """The tensor under ``key`` completed: attribute its critical
        path and fold per-rank lags into the EWMAs."""
        with self._lock:
            ent = self._pending.pop(key, None)
            if ent is None or len(ent[1]) < 2:
                return
            t_first, arrivals = ent
            last_rank = max(arrivals, key=arrivals.get)
            spread = arrivals[last_rank] - t_first
            for rank, t in arrivals.items():
                lag = t - t_first
                prev = self._lag.get(rank)
                self._lag[rank] = lag if prev is None else \
                    prev + self._alpha * (lag - prev)
            self._neg_samples += 1
            self._last_neg_t = time.monotonic()
        _READY_SPREAD.observe(spread)
        _CRITICAL_PATH.inc(1, rank=last_rank)

    def note_abandon(self, key: tuple):
        """Drop a pending tensor without attributing it (join-forced
        completion, stall shutdown — the arrival order is not a fair
        lag sample there)."""
        with self._lock:
            self._pending.pop(key, None)

    def reset_pending(self):
        """Elastic break: every in-flight negotiation just failed."""
        with self._lock:
            self._pending.clear()

    def drop_rank(self, rank: int):
        """A rank was promoted to lost: its frozen lag/wait EWMAs,
        score, and slow flag must stop contributing — a dead rank
        advertised as 'top straggler' forever would invert the very
        slow-vs-dead signal this scorer exists to provide (the
        _rank_metrics eviction mirror).  The next refresh() republishes
        its gauge as 0."""
        with self._lock:
            self._lag.pop(rank, None)
            self._wait.pop(rank, None)
            self._scores.pop(rank, None)
            self._flagged.discard(rank)

    def note_worker_phases(self,
                           per_rank: Dict[int, Dict[str, float]]):
        """Adopt MR/MA-carried per-rank phase summaries (the replay-
        mode attribution source)."""
        with self._lock:
            for rank, phases in per_rank.items():
                if "e2e" in phases:
                    self._wait[rank] = float(phases["e2e"])

    # -- scoring -------------------------------------------------------
    @staticmethod
    def _median(values: List[float]) -> float:
        return statistics.median(values) if values else 0.0

    def refresh(self) -> Dict[int, float]:
        """Recompute normalized scores from both sources, publish the
        hvd_straggler_score gauges, and fire the slow hooks on fresh
        threshold crossings.  Cold path (coordinator loop cadence)."""
        with self._lock:
            lags = dict(self._lag)
            waits = dict(self._wait)
            floor = self.min_lag_s
        scores: Dict[int, float] = {}
        if lags:
            base = max(self._median(list(lags.values())), floor)
            for rank, lag in lags.items():
                scores[rank] = 0.0 if lag < floor else lag / base
        if len(waits) >= 2:
            med = self._median(list(waits.values()))
            for rank, e2e in waits.items():
                gap = med - e2e
                s = 0.0 if gap < floor else gap / max(e2e, floor)
                if s > scores.get(rank, 0.0):
                    scores[rank] = s
        newly_slow: List[Tuple[int, float]] = []
        with self._lock:
            self._scores = scores
            self._last_refresh_t = time.monotonic()
            for rank, score in scores.items():
                if score >= self.threshold:
                    if rank not in self._flagged:
                        self._flagged.add(rank)
                        newly_slow.append((rank, score))
                elif score < self.threshold / 2.0:
                    self._flagged.discard(rank)
        for rank in range(self.size):
            _SCORE.set(round(scores.get(rank, 0.0), 3), rank=rank)
        for rank, score in newly_slow:
            _FLAGS.inc(1, rank=rank)
            logger.warning(
                "straggler: rank %d crossed the slow threshold "
                "(score %.2f >= %.2f)", rank, score, self.threshold)
            if _fr.ENABLED:
                _fr.record(_fr.STRAGGLER, rank=0, role="coord",
                           peer=rank, score=round(score, 3),
                           threshold=self.threshold)
            if _prof.ENABLED:
                # Why-is-it-slow: snapshot the profiler's last window
                # at the moment of the crossing (common/profiler.py
                # triggered capture — throttled, cold path).
                _prof.trigger_capture(
                    "straggler",
                    "rank %d score %.2f" % (rank, score))
            self._fire_slow_hook(rank, score)
        # Re-fire the hook (throttled) for ranks STILL flagged: the
        # slow-rank KV notice is a heartbeat, not an edge — consumers
        # (the elastic driver's migration policy) read "flagged right
        # now" as "notice fresher than the staleness bound", so a rank
        # that recovers simply stops being republished.  Logging and
        # the flag counter above stay crossing-only.
        with self._lock:
            still = [(r, scores.get(r, 0.0)) for r in self._flagged]
        now = time.monotonic()
        for rank, score in still:
            if now - self._last_slow_pub.get(rank, 0.0) >= \
                    _SLOW_REPUBLISH_S:
                self._fire_slow_hook(rank, score)
        return scores

    def _fire_slow_hook(self, rank: int, score: float):
        self._last_slow_pub[rank] = time.monotonic()
        if self._on_slow is not None:
            try:
                self._on_slow(rank, score)
            except Exception:
                logger.warning("slow-rank hook failed",
                               exc_info=True)

    # -- reading -------------------------------------------------------
    def top(self) -> Optional[Tuple[int, float]]:
        """(rank, score) of the current worst straggler, or None when
        nothing scores above zero."""
        with self._lock:
            if not self._scores:
                return None
            rank = max(self._scores, key=self._scores.get)
            score = self._scores[rank]
        return (rank, score) if score > 0.0 else None

    def scores(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._scores)

    def flagged(self) -> List[int]:
        with self._lock:
            return sorted(self._flagged)

    def snapshot(self) -> dict:
        """JSON-ready scorer state for /status."""
        with self._lock:
            return {
                "threshold": self.threshold,
                "min_lag_s": self.min_lag_s,
                "scores": {str(r): round(s, 3)
                           for r, s in sorted(self._scores.items())},
                "flagged": sorted(self._flagged),
                "lag_ewma_s": {str(r): round(v, 6)
                               for r, v in sorted(self._lag.items())},
                "wait_ewma_s": {str(r): round(v, 6)
                                for r, v in sorted(self._wait.items())},
                "negotiation_samples": self._neg_samples,
            }


# Arm from the environment at import: the knob rides the launcher env
# contract to every worker (the HOROVOD_FAILPOINTS precedent).
if _env.env_bool(_env.HOROVOD_STRAGGLER):
    configure(enabled=True)
