"""Response cache: the negotiation fast path.

The analog of the reference response cache (reference: response_cache.{h,cc}:
ResponseCache :45-102 — cache keyed by tensor name, HIT only when
device/dtype/shape/scale all match, else INVALID → renegotiation; and
CacheCoordinator :107-169 — in the reference, workers exchange hit
bitvectors with one or two bitwise-AND allreduces instead of a full
negotiation round; fast path in controller.cc:81-236).

This build's control plane is a star (workers push to a rank-0
coordinator over TCP), so the fast path is framed differently but buys
the same thing — O(small-constant) control bytes per steady-state step
instead of O(tensors) full request/response payloads:

  * The COORDINATOR owns bit assignment.  When it broadcasts a newly
    negotiated Response it attaches a fresh ``cache_bits`` entry per
    tensor; every worker stores the per-tensor response under that bit.
    Because bits are assigned in exactly one place, workers never have
    to agree on LRU/eviction order (the subtle invariant the reference
    maintains with symmetric caches + bitvector sync).
  * Workers whose next request for a tensor matches the cached
    signature send a 4-byte bit (CH frame) instead of the full request.
  * When EVERY participating rank contributed via bit, the coordinator
    broadcasts a CB frame: fused batches of bits in execution order.
    Workers reconstruct the fused Response locally from their caches.
  * Any full request for a cached tensor (signature change) forces the
    coordinator to evict + renegotiate, and the re-broadcast re-seeds
    everyone — self-healing, no eviction consensus needed.  Workers
    never evict on their own: EV frames (coordinator capacity-LRU or
    invalidation) are the only way entries leave a worker cache, so
    worker caches always cover the coordinator's live bits.

On TPU the cache is *load-bearing*: a cache hit means the fused batch
signature is unchanged, so the compiled XLA executable for the batch is
reused without recompilation (SURVEY §7: response-cache hits map to
executable-cache hits).
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from . import metrics
from .message import Request, RequestType, Response, ResponseType

_CACHE_EVENTS = metrics.counter(
    "hvd_response_cache_total",
    "Worker response-cache events (hit / miss / invalidate / evict); "
    "a hit also implies a compiled-executable reuse on TPU")

# Response types that participate in the cache (JOIN/BARRIER/ERROR are
# control-flow, never cached — reference response_cache.cc caches the
# data collectives only).  ALLTOALL is excluded since round 5: its
# response carries the send-split matrix, and splits may legally change
# call-to-call under an unchanged signature — a cached response would
# serve stale recv splits.  Full negotiation per alltoall is still one
# round cheaper than the pre-round-5 CH + data-plane split-allgather.
CACHEABLE = {ResponseType.ALLREDUCE, ResponseType.ADASUM,
             ResponseType.ALLGATHER, ResponseType.BROADCAST,
             ResponseType.REDUCESCATTER}

_RESP_TO_REQ = {
    ResponseType.ALLREDUCE: RequestType.ALLREDUCE,
    ResponseType.ALLGATHER: RequestType.ALLGATHER,
    ResponseType.BROADCAST: RequestType.BROADCAST,
    ResponseType.ADASUM: RequestType.ADASUM,
    ResponseType.ALLTOALL: RequestType.ALLTOALL,
    ResponseType.REDUCESCATTER: RequestType.REDUCESCATTER,
}


def request_signature(req: Request) -> tuple:
    """Everything that must be unchanged for a cached response to be
    valid for this rank (reference response_cache.cc:49-87 checks
    device/dtype/shape/prescale/postscale)."""
    return (tuple(req.tensor_shape), int(req.tensor_type), req.root_rank,
            req.prescale_factor, req.postscale_factor,
            req.process_set_id, req.reduce_op, int(req.request_type),
            tuple(req.process_set_ranks))


def signature_to_request(sig: tuple, rank: int, name: str,
                         first_dim: Optional[int] = None) -> Request:
    """Reconstruct a Request from a cached signature (coordinator side:
    used when a cache-bit contribution must be merged with full requests
    in a degraded round).  ``first_dim`` overrides shape[0] for ops with
    per-rank first dimensions (allgather)."""
    (shape, dtype, root, pre, post, psid, op, rtype, psr) = sig
    if first_dim is not None and shape:
        shape = (first_dim,) + tuple(shape[1:])
    return Request(request_rank=rank, request_type=RequestType(rtype),
                   tensor_name=name, tensor_shape=tuple(shape),
                   tensor_type=dtype, root_rank=root, prescale_factor=pre,
                   postscale_factor=post, process_set_id=psid,
                   reduce_op=op, process_set_ranks=tuple(psr))


def split_response(resp: Response, world_size: int) -> List[Response]:
    """Slice a (possibly fused) Response into per-tensor responses.

    For fused allgathers the tensor_sizes list is the concatenation of
    per-GROUP-rank row counts per tensor (group = process-set ranks
    when given, else the world; see fusion.py) — slice accordingly.
    """
    out = []
    per_sizes = 0
    group = len(resp.process_set_ranks) or world_size
    if resp.response_type == ResponseType.ALLGATHER and group > 0 \
            and len(resp.tensor_sizes) == group * len(resp.tensor_names):
        per_sizes = group
    for i, name in enumerate(resp.tensor_names):
        out.append(Response(
            response_type=resp.response_type,
            tensor_names=[name],
            tensor_type=resp.tensor_type,
            tensor_sizes=(resp.tensor_sizes[i * per_sizes:
                                            (i + 1) * per_sizes]
                          if per_sizes else list(resp.tensor_sizes)),
            prescale_factor=resp.prescale_factor,
            postscale_factor=resp.postscale_factor,
            process_set_id=resp.process_set_id,
            root_rank=resp.root_rank,
            reduce_op=resp.reduce_op,
            tensor_shapes=([resp.tensor_shapes[i]]
                           if i < len(resp.tensor_shapes) else []),
            process_set_ranks=resp.process_set_ranks,
        ))
    return out


def merge_responses(parts: List[Response]) -> Response:
    """Merge per-tensor cached responses into one fused Response —
    the worker-side inverse of the coordinator's fusion plan (must
    mirror fusion.py's concatenation order exactly)."""
    first = parts[0]
    merged = Response(
        response_type=first.response_type,
        tensor_names=[], tensor_type=first.tensor_type,
        tensor_sizes=[], prescale_factor=first.prescale_factor,
        postscale_factor=first.postscale_factor,
        process_set_id=first.process_set_id, root_rank=first.root_rank,
        reduce_op=first.reduce_op, tensor_shapes=[],
        process_set_ranks=first.process_set_ranks)
    for p in parts:
        merged.tensor_names.extend(p.tensor_names)
        merged.tensor_sizes.extend(p.tensor_sizes)
        merged.tensor_shapes.extend(p.tensor_shapes)
    return merged


class WorkerResponseCache:
    """Per-rank cache: name → (coordinator bit, per-tensor response,
    this rank's request signature).  Entries without a signature (this
    rank never submitted the tensor — e.g. non-members of a process set,
    joined ranks) still resolve CB bits but never produce hits.

    Workers NEVER evict on their own: eviction follows coordinator EV
    frames exclusively, so the worker's entry set is always a superset
    of the coordinator's live bits no matter how per-rank capacity
    knobs are (mis)configured — a CB frame can then never reference a
    bit the worker dropped unilaterally.  The coordinator's capacity
    bounds growth for everyone."""

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._lock = threading.Lock()
        # name -> [bit, response, sig-or-None]
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._bit_names: Dict[int, str] = {}

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def lookup_bit(self, req: Request,
                   count_miss: bool = True) -> Optional[int]:
        """Bit for a HIT, else None.  A signature mismatch (INVALID)
        drops the local entry so the full request goes out and the
        coordinator renegotiates.  Entries are keyed by
        (process_set_id, name) — the same name may be cached for two
        process sets at once.

        ``count_miss=False`` suppresses the miss metric only: the
        inline fast-path probe passes it because a missed request
        falls back to the negotiation queue, where the cycle's own
        lookup counts the SAME logical miss — counting both would
        inflate misses ~2x.  Hits/invalidations happen exactly once
        (a hit short-circuits the second lookup; an invalidation
        deletes the entry) so they always count."""
        key = (req.process_set_id, req.tensor_name)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                if count_miss:
                    _CACHE_EVENTS.inc(1, event="miss")
                return None
            bit, _, sig = ent
            if sig is None or sig != request_signature(req):
                del self._entries[key]
                self._bit_names.pop(bit, None)
                _CACHE_EVENTS.inc(1, event="invalidate")
                return None
            _CACHE_EVENTS.inc(1, event="hit")
            return bit

    def insert(self, name: str, bit: int, response: Response,
               sig: Optional[tuple]):
        with self._lock:
            old = self._entries.pop(name, None)
            if old is not None:
                self._bit_names.pop(old[0], None)
            self._entries[name] = [bit, response, sig]
            self._bit_names[bit] = name

    def response_for_bit(self, bit: int) -> Optional[Response]:
        with self._lock:
            name = self._bit_names.get(bit)
            if name is None:
                return None
            return self._entries[name][1]

    def evict_bits(self, bits: List[int]):
        with self._lock:
            for b in bits:
                name = self._bit_names.pop(b, None)
                if name is not None:
                    self._entries.pop(name, None)
                    _CACHE_EVENTS.inc(1, event="evict")

    def debug_bits(self):
        """bit -> key snapshot for desync diagnostics."""
        with self._lock:
            return dict(sorted(self._bit_names.items()))

    def __len__(self):
        with self._lock:
            return len(self._entries)


class CoordinatorCache:
    """Rank-0 cache: authoritative bit assignment + enough signature
    state to synthesize a rank's request when a cache-bit contribution
    lands in a degraded (partially-uncached) round.

    Bits are monotonically increasing and never reused, so a late CH
    frame racing an eviction still resolves through the tombstone map
    (bounded FIFO; overflowing it would take ~64k evictions inside one
    round-trip window)."""

    TOMBSTONE_CAP = 65536

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        # name -> [bit, response(per-tensor), sig, group_id]
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._bit_names: Dict[int, str] = {}
        # bit -> (name, sig, sizes, group_id) for recently evicted bits
        self._tombstones: "OrderedDict[int, tuple]" = OrderedDict()
        self._next_bit = 0
        self._disabled = False

    @property
    def enabled(self) -> bool:
        return self.capacity > 0 and not self._disabled

    def set_enabled(self, flag: bool) -> List[int]:
        """Runtime toggle (autotuner cache on/off).  Disabling evicts
        every live entry; the returned bits must be EV-broadcast so
        worker caches drain through the normal protocol."""
        evicted: List[int] = []
        if not flag and not self._disabled:
            for name in list(self._entries):
                bit = self.evict_name(name)
                if bit is not None:
                    evicted.append(bit)
        self._disabled = not flag
        return evicted

    def get(self, name: str) -> Optional[list]:
        return self._entries.get(name)

    def has(self, name: str) -> bool:
        return name in self._entries

    def resolve_bit(self, bit: int):
        """Returns (live, name, sig, sizes, group_id) or None.  ``live``
        False means the bit was evicted (tombstone): the contribution is
        honored but forces the full negotiation path."""
        name = self._bit_names.get(bit)
        if name is not None:
            # LRU: a bit contribution marks the tensor hot, so capacity
            # eviction prefers tensors no rank is actively using
            # (reference response_cache.h:45-102 LRU semantics).
            self._entries.move_to_end(name)
            ent = self._entries[name]
            return True, name, ent[2], ent[1].tensor_sizes, ent[3]
        tomb = self._tombstones.get(bit)
        if tomb is not None:
            return (False,) + tomb
        return None

    def insert(self, name: str, response: Response, sig: tuple,
               group_id: int, pending_names=()) -> Tuple[int, List[int]]:
        """Insert/replace; returns (bit, evicted_bits).  Capacity
        eviction skips tensors with an in-flight negotiation round
        (``pending_names``) so their bits stay resolvable."""
        evicted: List[int] = []
        old = self._entries.pop(name, None)
        if old is not None:
            self._tombstone(old[0], name, old[2],
                            old[1].tensor_sizes, old[3])
            self._bit_names.pop(old[0], None)
            evicted.append(old[0])
        while len(self._entries) >= self.capacity > 0:
            victim = None
            for cand in self._entries:
                if cand not in pending_names:
                    victim = cand
                    break
            if victim is None:
                break  # everything in flight; let the cache overgrow
            ent = self._entries.pop(victim)
            self._tombstone(ent[0], victim, ent[2],
                            ent[1].tensor_sizes, ent[3])
            self._bit_names.pop(ent[0], None)
            evicted.append(ent[0])
        bit = self._next_bit
        self._next_bit += 1
        self._entries[name] = [bit, response, sig, group_id]
        self._bit_names[bit] = name
        return bit, evicted

    def evict_name(self, name: str) -> Optional[int]:
        ent = self._entries.pop(name, None)
        if ent is None:
            return None
        bit, resp, sig, gid = ent
        self._tombstone(bit, name, sig, resp.tensor_sizes, gid)
        self._bit_names.pop(bit, None)
        return bit

    def _tombstone(self, bit, name, sig, sizes, gid):
        self._tombstones[bit] = (name, sig, sizes, gid)
        while len(self._tombstones) > self.TOMBSTONE_CAP:
            self._tombstones.popitem(last=False)

    def clear_tombstones_for(self, name: str):
        dead = [b for b, t in self._tombstones.items() if t[0] == name]
        for b in dead:
            del self._tombstones[b]

    def __len__(self):
        return len(self._entries)
