"""LRU response cache with bit-indexed coordination.

Mirrors the reference response cache (reference: response_cache.{h,cc}:
ResponseCache :45-102 — LRU keyed by tensor name, HIT only when
device/dtype/shape/scale all match, else INVALID → eviction; and
CacheCoordinator :107-169 — workers exchange hit bitvectors with one or
two bitwise-AND allreduces instead of a full negotiation round).

On TPU the cache is *load-bearing*: a cache hit means the fused batch
signature is unchanged, so the compiled XLA executable for the batch is
reused without recompilation (SURVEY §7: response-cache hits map to
executable-cache hits).
"""

import enum
from collections import OrderedDict
from typing import Dict, List, Optional, Set

from .message import Request, Response


class CacheState(enum.IntEnum):
    MISS = 0
    HIT = 1
    INVALID = 2


class ResponseCache:
    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        # name -> (bit, response, params signature)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._bits_dirty = False

    def _signature(self, req: Request):
        return (req.tensor_shape, req.tensor_type, req.root_rank,
                req.prescale_factor, req.postscale_factor,
                req.process_set_id, req.reduce_op, int(req.request_type))

    def cached(self, req: Request) -> CacheState:
        ent = self._entries.get(req.tensor_name)
        if ent is None:
            return CacheState.MISS
        _, _, sig = ent
        if sig != self._signature(req):
            return CacheState.INVALID
        return CacheState.HIT

    def put(self, req: Request, resp: Response):
        if req.tensor_name in self._entries:
            self._entries.move_to_end(req.tensor_name)
            bit = self._entries[req.tensor_name][0]
            self._entries[req.tensor_name] = (
                bit, resp, self._signature(req))
            return
        if len(self._entries) >= self.capacity > 0:
            self._entries.popitem(last=False)
            self._bits_dirty = True
        self._entries[req.tensor_name] = (
            len(self._entries), resp, self._signature(req))
        self._bits_dirty = True

    def get_response(self, name: str) -> Optional[Response]:
        ent = self._entries.get(name)
        if ent is None:
            return None
        self._entries.move_to_end(name)
        return ent[1]

    def erase(self, name: str):
        if name in self._entries:
            del self._entries[name]
            self._bits_dirty = True

    def update_bits(self):
        """Reassign dense bit positions after eviction (bit-index
        compaction, as the reference does on capacity change)."""
        if self._bits_dirty:
            for i, (name, (_, resp, sig)) in enumerate(
                    list(self._entries.items())):
                self._entries[name] = (i, resp, sig)
            self._bits_dirty = False

    def peek_bit(self, name: str) -> Optional[int]:
        ent = self._entries.get(name)
        return None if ent is None else ent[0]

    def name_of_bit(self, bit: int) -> Optional[str]:
        for name, (b, _, _) in self._entries.items():
            if b == bit:
                return name
        return None

    def num_active_bits(self) -> int:
        return len(self._entries)

    def hit_bitvector(self, requests: List[Request]) -> Optional[int]:
        """Bitvector of cache hits for this cycle's requests, or None if
        any request MISSed/INVALIDated (forces full negotiation)."""
        self.update_bits()
        bits = 0
        for req in requests:
            state = self.cached(req)
            if state != CacheState.HIT:
                return None
            bits |= 1 << self.peek_bit(req.tensor_name)
        return bits

    def responses_for_bits(self, bits: int) -> List[Response]:
        self.update_bits()
        out = []
        for name, (b, resp, _) in self._entries.items():
            if bits & (1 << b):
                out.append(resp)
        return out


class CacheCoordinator:
    """Aggregates per-rank hit/invalid bit sets; in multiprocess mode the
    sets are combined with bitwise-AND/OR exchanges over the control
    channel (reference: CacheCoordinator::sync)."""

    def __init__(self):
        self.hit_bits: Set[int] = set()
        self.invalid_bits: Set[int] = set()
        self.should_shutdown = False
        self.uncached_in_queue = False

    def record_hit(self, bit: int):
        self.hit_bits.add(bit)

    def record_invalid(self, bit: int):
        self.invalid_bits.add(bit)
        self.hit_bits.discard(bit)

    def combine(self, others: List["CacheCoordinator"]):
        for o in others:
            self.hit_bits &= o.hit_bits
            self.invalid_bits |= o.invalid_bits
            self.should_shutdown |= o.should_shutdown
            self.uncached_in_queue |= o.uncached_in_queue
        self.hit_bits -= self.invalid_bits
