"""Elastic training: the worker-side state machine and retry loop.

The analog of the reference's ``horovod/common/elastic.py`` (reference:
common/elastic.py:26-168 — ``State``/``ObjectState``/``run_fn``): user
training state registers commit/restore/sync hooks; the ``run_fn``
wrapper retries the training function across membership changes,
restoring the last committed state after an internal error and
re-initializing the runtime after every world change.

TPU-specific delta: host-update notification is a *pull* at
``state.commit()``/``check_host_updates()`` time — workers poll the
driver's rendezvous KV version key — instead of the reference's
per-worker push RPC service (runner/elastic/worker.py).  Commit already
quiesces training, so the poll adds one small HTTP GET over DCN at
commit cadence and removes a listening socket from every worker.
"""

import io
import logging
import queue
import time
from typing import Callable, Dict, List, Optional

from . import metrics
from .exceptions import HorovodInternalError, HostsUpdatedInterrupt

logger = logging.getLogger("horovod_tpu.elastic")

# Recovery-pipeline phase timings (docs/failure_recovery.md): the
# retry loop below observes restore/reset, the chaos MTTR drill and
# the elastic driver path observe detect/resume — one histogram tells
# the whole detect → restore → resume story.
RECOVERY_SECONDS = metrics.histogram(
    "hvd_recovery_seconds",
    "Failure-recovery pipeline wall time, by phase (detect = fault to "
    "survivor unwind; restore = state restore; reset = runtime "
    "re-init; resume = restore to first post-restore step)")


class HostUpdateSource:
    """Where a worker learns that cluster membership changed.

    The default implementation polls the elastic rendezvous version key
    (filled in by ``horovod_tpu.runner.elastic.worker``); tests inject a
    fake with a local queue.
    """

    def has_update(self) -> bool:
        raise NotImplementedError


class QueueHostUpdateSource(HostUpdateSource):
    """Test/fake source: push updates into a queue."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()

    def put(self):
        self._q.put(1)

    def has_update(self) -> bool:
        got = False
        try:
            while True:
                self._q.get_nowait()
                got = True
        except queue.Empty:
            pass
        return got


_host_update_source: Optional[HostUpdateSource] = None


def set_host_update_source(source: Optional[HostUpdateSource]):
    global _host_update_source
    _host_update_source = source


def get_host_update_source() -> Optional[HostUpdateSource]:
    return _host_update_source


class State:
    """State representing a snapshot of the program for elastic restore.

    Subclasses implement ``save``/``restore``/``sync`` for their
    framework's objects (reference: common/elastic.py:26-109).
    """

    def __init__(self, **kwargs):
        self._host_messages: "queue.Queue" = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks: List[Callable] = []

    def register_reset_callbacks(self, callbacks: List[Callable]):
        """Callbacks invoked after a reset (e.g. rescale the learning
        rate to the new world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self):
        self._host_messages.put(1)

    def commit(self):
        """Commit the current state and check for membership changes.

        Raises ``HostsUpdatedInterrupt`` when hosts were added/removed
        so the caller's train loop unwinds to ``run_fn``'s retry loop.
        """
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        updated = False
        # External (driver) notification channel.
        src = get_host_update_source()
        if src is not None and src.has_update():
            updated = True
        # In-process notifications (tests, embedded drivers).
        try:
            while True:
                self._host_messages.get_nowait()
                updated = True
        except queue.Empty:
            pass
        if updated:
            raise HostsUpdatedInterrupt()

    def save(self):
        """Snapshot the state in memory (cheap, local)."""
        raise NotImplementedError()

    def restore(self):
        """Restore the last committed snapshot."""
        raise NotImplementedError()

    def sync(self):
        """Synchronize state across workers (broadcast from rank 0)."""
        raise NotImplementedError()

    def reset(self):
        """Rebuild any world-size-dependent objects after re-init."""
        pass

    # -- durable checkpoint protocol (horovod_tpu.checkpoint) ---------
    def durable_state_dict(self) -> Dict[str, object]:
        """Flat ``{item_name: host_value}`` view of the committed
        snapshot, for the durable checkpoint subsystem.  Names are
        namespaced (``obj/...``, ``tree/...``) so subclasses can
        compose; values must pickle bit-exactly (numpy, python
        scalars).  The dict's values must be REBOUND (not mutated) by
        later ``save()`` calls — the async writer serializes the
        captured references while training runs ahead."""
        raise NotImplementedError()

    def load_durable_state_dict(self, items: Dict[str, object]):
        """Inverse of :meth:`durable_state_dict`: install the restored
        items as BOTH the committed snapshot and the live attributes
        (a restore is a commit you didn't have to compute)."""
        raise NotImplementedError()


class ObjectState(State):
    """State for a dict of picklable python objects, synchronized via
    ``broadcast_object`` (reference: common/elastic.py:112-146)."""

    def __init__(self, bcast_object: Callable, get_rank: Callable,
                 **kwargs):
        self._bcast_object = bcast_object
        self._rank = get_rank
        self._saved_state: Dict = kwargs
        self._set_attrs()
        super().__init__()

    def save(self):
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = getattr(self, attr)
        self._saved_state = new_state

    def restore(self):
        self._set_attrs()

    def sync(self):
        if self._saved_state:
            self._saved_state = self._bcast_object(self._saved_state)
            self._set_attrs()

    def _set_attrs(self):
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)

    def durable_state_dict(self) -> Dict[str, object]:
        return {"obj/" + k: v for k, v in self._saved_state.items()}

    def load_durable_state_dict(self, items: Dict[str, object]):
        restored = {k[len("obj/"):]: v for k, v in items.items()
                    if k.startswith("obj/")}
        # Items registered at construction but absent from the
        # checkpoint (a new attribute added since it was written) keep
        # their constructor values instead of vanishing.
        merged = dict(self._saved_state)
        merged.update(restored)
        self._saved_state = merged
        self._set_attrs()


def run_fn(func: Callable, reset: Callable):
    """Wrap ``func(state, ...)`` in the elastic retry loop (reference:
    common/elastic.py:147-168).

    * ``HorovodInternalError`` → restore last committed state, reset,
      retry;
    * ``HostsUpdatedInterrupt`` → keep current (committed) state, reset,
      retry;
    * normal return → done.
    """

    def wrapper(state, *args, **kwargs):
        notification_manager.init()
        notification_manager.register_listener(state)
        skip_sync = False
        try:
            while True:
                try:
                    # sync() stays inside the try: a rank dying during
                    # the post-reset broadcast must retry, not kill the
                    # worker (reference keeps sync in the retried body).
                    if not skip_sync:
                        state.sync()
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    logger.info("elastic: internal error; restoring last "
                                "committed state")
                    t0 = time.perf_counter()
                    state.restore()
                    RECOVERY_SECONDS.observe(time.perf_counter() - t0,
                                             phase="restore")
                    skip_sync = False
                except HostsUpdatedInterrupt as e:
                    logger.info("elastic: hosts updated; re-initializing")
                    skip_sync = e.skip_sync
                t0 = time.perf_counter()
                reset()
                state.on_reset()
                RECOVERY_SECONDS.observe(time.perf_counter() - t0,
                                         phase="reset")
        finally:
            notification_manager.remove_listener(state)

    return wrapper


class WorkerNotificationManager:
    """Tracks State listeners so external drivers can signal host
    updates into every active State (reference:
    runner/elastic/worker.py WorkerNotificationManager)."""

    def __init__(self):
        self._listeners: List[State] = []
        self._initialized = False

    def init(self):
        self._initialized = True

    def register_listener(self, state: State):
        self._listeners.append(state)

    def remove_listener(self, state: State):
        if state in self._listeners:
            self._listeners.remove(state)

    def handle_hosts_updated(self):
        for listener in self._listeners:
            listener.on_hosts_updated()


notification_manager = WorkerNotificationManager()
