"""Autotuner: Bayesian optimization of runtime knobs from live
throughput.

Reference: common/parameter_manager.{h,cc} (251+528) — tunables scored
by bytes/sec over sampling windows, warmup samples discarded, best
params adopted when tuning converges; the search is JOINT over the
continuous knobs (fusion-threshold-MB × cycle-time-ms, GP + Expected
Improvement, BayesianParameter :186-220) and the categorical knobs
(hierarchical allreduce on/off, cache on/off — CategoricalParameterEntry
:140-184), and the winning parameters are synchronized to every rank
(Controller::SynchronizeParameters, controller.cc:39-53).

TPU-native deltas:
  * fusion planning happens ONLY on the rank-0 coordinator (workers
    execute broadcast fused batches), so the fusion threshold needs no
    cross-rank synchronization — but the categorical knobs are
    worker-side data-plane choices, so the coordinator announces them
    through PA frames positioned in the response stream (every worker
    flips between the same two batches; controller_net.py);
  * the reference's cycle-time knob exists because its background loop
    polls on a fixed cadence (operations.cc:587 1 ms sleep); this
    runtime is event-driven (wakes on submit), so there is no polling
    cadence to tune — ``cycle_time_ms`` is carried for API parity but
    fixed;
  * categorical search: one GP per category combination, explored
    round-robin window-by-window, best (combo, fusion) adopted at
    convergence — the reference's nested Categorical/Bayesian layout
    with the same effect.
"""

import logging
import time
from typing import Callable, Dict, Optional

import numpy as np

from .optim.bayesian_optimization import BayesianOptimization

logger = logging.getLogger("horovod_tpu.autotune")

MB = 1024 * 1024

FUSION_MB_BOUNDS = (1.0, 128.0)

# (hierarchical allreduce, cache enabled) combinations, classic
# defaults first so warmup windows run the stock configuration.
_COMBOS = ((False, True), (True, True), (False, False), (True, False))


class ParameterManager:
    def __init__(self, warmup_samples: int = 3,
                 steps_per_sample: int = 10,
                 bayes_opt_max_samples: int = 20,
                 gp_noise: float = 0.8,
                 initial_fusion_bytes: int = 64 * MB,
                 initial_cycle_ms: float = 1.0,
                 log_path: Optional[str] = None,
                 tune_categorical: bool = True,
                 fixed_hierarchical: Optional[bool] = None,
                 fixed_cache: Optional[bool] = None,
                 on_update: Optional[Callable] = None):
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = bayes_opt_max_samples
        self._on_update = on_update
        # Explicitly-set knobs are held fixed during tuning (the
        # reference likewise only tunes parameters the user left
        # unset, parameter_manager.cc SetAutoTuning semantics).
        combos = _COMBOS if tune_categorical else (_COMBOS[0],)
        combos = tuple(
            c for c in combos
            if (fixed_hierarchical is None or c[0] == fixed_hierarchical)
            and (fixed_cache is None or c[1] == fixed_cache))
        self._combos = combos or ((bool(fixed_hierarchical),
                                   fixed_cache is not False),)
        self._bo = {c: BayesianOptimization(bounds=[FUSION_MB_BOUNDS],
                                            gp_noise=gp_noise)
                    for c in self._combos}
        self._combo_idx = 0
        self.fusion_threshold_bytes = initial_fusion_bytes
        self.cycle_time_ms = initial_cycle_ms   # API parity; fixed
        self._current = np.array([initial_fusion_bytes / MB])
        self._samples_taken = 0
        self._steps = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._done = False
        # Monotonic version: bumped whenever the categorical params
        # change, so the coordinator knows when to emit a PA frame.
        self.params_version = 0
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            self._log.write("sample,fusion_mb,hierarchical,cache,"
                            "score_bytes_per_sec,is_best\n")

    @property
    def active(self) -> bool:
        return not self._done

    @property
    def categorical_params(self) -> Dict[str, bool]:
        h, c = self._combos[self._combo_idx]
        return {"hierarchical": h, "cache": c}

    def record_step(self, nbytes: int):
        """One negotiation round completed, moving ``nbytes`` of fused
        tensor payload.  Drives the sampling window."""
        if self._done:
            return
        self._bytes += nbytes
        self._steps += 1
        if self._steps < self._steps_per_sample:
            return
        elapsed = max(time.monotonic() - self._window_start, 1e-6)
        score = self._bytes / elapsed
        self._steps = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._advance(score)

    def _advance(self, score: float):
        if self._warmup_remaining > 0:
            # Warmup windows pollute the score (compilation, cold
            # caches); discard them (reference warmup discard).
            self._warmup_remaining -= 1
            return
        combo = self._combos[self._combo_idx]
        bo = self._bo[combo]
        bo.add_sample(self._current, score)
        self._samples_taken += 1
        best = bo.best
        is_best = best is not None and np.allclose(best[0], self._current)
        if self._log:
            self._log.write(
                f"{self._samples_taken},{self._current[0]:.2f},"
                f"{int(combo[0])},{int(combo[1])},{score:.1f},"
                f"{int(bool(is_best))}\n")
            self._log.flush()
        if self._samples_taken >= self._max_samples:
            self._converge()
            return
        # Round-robin the category combinations; each keeps its own GP
        # over the fusion threshold.
        next_idx = (self._combo_idx + 1) % len(self._combos)
        next_bo = self._bo[self._combos[next_idx]]
        self._apply(next_idx, next_bo.next_sample())

    def _converge(self):
        best_combo, best_params, best_score = None, None, -np.inf
        for combo, bo in self._bo.items():
            if bo.best is not None and bo.best[1] > best_score:
                best_combo, (best_params, best_score) = combo, bo.best
        if best_combo is None:
            best_combo, best_params = self._combos[self._combo_idx], \
                self._current
            best_score = 0.0
        self._apply(self._combos.index(best_combo), best_params)
        self._done = True
        # Convergence is an announcable event even when the winning
        # combo is the one already applied: the final PA frame carries
        # tuning_active=false, which is what releases the steady-state
        # replay hold on every rank (warmup -> freeze -> replay).
        self.params_version += 1
        logger.info(
            "autotune converged: fusion=%.1fMB hierarchical=%s cache=%s "
            "(%.1f MB/s)", best_params[0], best_combo[0], best_combo[1],
            best_score / MB)
        if self._log:
            self._log.close()
            self._log = None

    def _apply(self, combo_idx: int, params):
        if combo_idx != self._combo_idx:
            self._combo_idx = combo_idx
            self.params_version += 1
        self._current = np.asarray(params, dtype=np.float64)
        self.fusion_threshold_bytes = int(self._current[0] * MB)
        if self._on_update:
            self._on_update(self.fusion_threshold_bytes,
                            self.cycle_time_ms, self.categorical_params)
