"""Autotuner: Bayesian optimization of runtime knobs from live
throughput.

Reference: common/parameter_manager.{h,cc} (251+528) — tunables scored
by bytes/sec over sampling windows, warmup samples discarded, best
params adopted when tuning converges; joint fusion-threshold ×
cycle-time search via GP + Expected Improvement
(BayesianParameter :186-220).

TPU-native deltas:
  * fusion planning happens ONLY on the rank-0 coordinator (workers
    execute broadcast fused batches), so the fusion threshold needs no
    cross-rank synchronization protocol — the manager lives in the
    CoordinatorServer and retunes its threshold in place;
  * the reference's cycle-time knob exists because its background loop
    polls on a fixed cadence (operations.cc:587 1 ms sleep); this
    runtime is event-driven (wakes on submit), so there is no polling
    cadence to tune — the search space is fusion threshold only, and
    ``cycle_time_ms`` is carried for API parity but fixed.
"""

import logging
import time
from typing import Callable, Optional

import numpy as np

from .optim.bayesian_optimization import BayesianOptimization

logger = logging.getLogger("horovod_tpu.autotune")

MB = 1024 * 1024

FUSION_MB_BOUNDS = (1.0, 128.0)


class ParameterManager:
    def __init__(self, warmup_samples: int = 3,
                 steps_per_sample: int = 10,
                 bayes_opt_max_samples: int = 20,
                 gp_noise: float = 0.8,
                 initial_fusion_bytes: int = 64 * MB,
                 initial_cycle_ms: float = 1.0,
                 log_path: Optional[str] = None,
                 on_update: Optional[Callable] = None):
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = bayes_opt_max_samples
        self._on_update = on_update
        self._bo = BayesianOptimization(
            bounds=[FUSION_MB_BOUNDS], gp_noise=gp_noise)
        self.fusion_threshold_bytes = initial_fusion_bytes
        self.cycle_time_ms = initial_cycle_ms   # API parity; fixed
        self._current = np.array([initial_fusion_bytes / MB])
        self._samples_taken = 0
        self._steps = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._done = False
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            self._log.write("sample,fusion_mb,score_bytes_per_sec,"
                            "is_best\n")

    @property
    def active(self) -> bool:
        return not self._done

    def record_step(self, nbytes: int):
        """One negotiation round completed, moving ``nbytes`` of fused
        tensor payload.  Drives the sampling window."""
        if self._done:
            return
        self._bytes += nbytes
        self._steps += 1
        if self._steps < self._steps_per_sample:
            return
        elapsed = max(time.monotonic() - self._window_start, 1e-6)
        score = self._bytes / elapsed
        self._steps = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self._advance(score)

    def _advance(self, score: float):
        if self._warmup_remaining > 0:
            # Warmup windows pollute the score (compilation, cold
            # caches); discard them (reference warmup discard).
            self._warmup_remaining -= 1
            return
        self._bo.add_sample(self._current, score)
        self._samples_taken += 1
        best = self._bo.best
        is_best = best is not None and np.allclose(best[0],
                                                   self._current)
        if self._log:
            self._log.write(
                f"{self._samples_taken},{self._current[0]:.2f},"
                f"{score:.1f},{int(bool(is_best))}\n")
            self._log.flush()
        if self._samples_taken >= self._max_samples:
            # Converged: adopt the best-observed parameters for the
            # rest of the run.
            params, best_score = best
            self._apply(params)
            self._done = True
            logger.info(
                "autotune converged: fusion=%.1fMB (%.1f MB/s)",
                params[0], best_score / MB)
            if self._log:
                self._log.close()
                self._log = None
            return
        self._apply(self._bo.next_sample())

    def _apply(self, params):
        self._current = np.asarray(params, dtype=np.float64)
        self.fusion_threshold_bytes = int(self._current[0] * MB)
        if self._on_update:
            self._on_update(self.fusion_threshold_bytes,
                            self.cycle_time_ms)
