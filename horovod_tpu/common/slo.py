"""SLO plane: is the job meeting its throughput target, and how fast
is it burning budget when it is not.

The straggler observatory ranks peers *relative to each other*; this
module holds the runtime to an *absolute* service level.  Two SLIs,
both fed from sites the hot path already instruments:

* **steps/s** — completed collective operations per second (the
  ``hvd_worker_op_rate`` vocabulary), target
  ``HOROVOD_SLO_STEPS_PER_S``;
* **cycle time** — controller cycle seconds (the
  ``hvd_controller_cycle_seconds`` population), target
  ``HOROVOD_SLO_CYCLE_SECONDS``.

Each SLI is evaluated over a SHORT and a LONG sliding window
(``HOROVOD_SLO_WINDOW_SHORT`` / ``_LONG``) and converted to a burn
rate: ``shortfall / budget``, where shortfall is the normalized miss
against the target and budget (``HOROVOD_SLO_BUDGET``) is the
tolerated fractional miss.  A burn of 1.0 means "missing by exactly
the tolerated amount"; 2.0 means burning budget twice as fast as
sustainable.  The classic SRE multi-window rule kills both failure
modes of single-window alerting: an alert fires only when BOTH
windows burn above ``HOROVOD_SLO_BURN_THRESHOLD`` — the short window
makes it fast, the long window makes it real.

On a burn crossing the plane (a) increments
``hvd_slo_burn_alerts_total``, (b) records a flight-recorder SLO_BURN
event, (c) asks the sampling profiler for a triggered capture (so the
postmortem carries *why* throughput fell, not just that it did), and
(d) calls an optional hook — rank 0 wires it to a rendezvous KV
notice that ``runner/elastic/driver.py`` folds into
``ElasticPolicy.Signals`` (cycle_time_s / steps_per_s — consumed
read-only this PR; the SLO-driven controller is ROADMAP item 4).

Cost contract: the two feeder sites (cycle end, op completion) are
written ``if _slo.ENABLED and tracker is not None: tracker.note_*``
— one module-attribute check when disabled, the straggler/flight
recorder precedent, pinned by tests/test_slo.py.  ``note_*`` itself
is an O(1) deque append under a plain leaf lock shared with the ~1 Hz
evaluator — the lock exists because CPython raises "deque mutated
during iteration" when an append lands mid-scan, and an uncontended
acquire is nanoseconds; nothing else is ever taken while holding it.
"""

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from . import env as _env
from . import flight_recorder as _fr
from . import metrics
from . import profiler as _prof

logger = logging.getLogger("horovod_tpu.slo")

# THE disabled-path gate: feeder sites check this one module attribute
# first.  configure()/reset() are the only writers.
ENABLED = False

_EVAL_INTERVAL_S = 1.0
_ALERT_REFIRE_S = 30.0   # a still-burning alert re-notifies at most
                         # this often (the hook/KV path, not the gauge)
_MAX_OPS = 262144        # op timestamps retained (≈ minutes at 1k/s)
_MAX_CYCLES = 32768      # (t, dt) cycle samples retained

_STEPS = metrics.gauge(
    "hvd_slo_steps_per_s",
    "Achieved throughput SLI (completed collective ops/s) over the "
    "short and long SLO windows, by rank")
_CYCLE = metrics.gauge(
    "hvd_slo_cycle_seconds",
    "Achieved cycle-time SLI (mean controller cycle seconds) over the "
    "short and long SLO windows, by rank")
_BURN = metrics.gauge(
    "hvd_slo_burn_rate",
    "Error-budget burn rate (normalized shortfall / budget) per SLI "
    "and window, by rank; >= the threshold in BOTH windows -> alert")
_ALERTS = metrics.counter(
    "hvd_slo_burn_alerts_total",
    "Multi-window SLO burn-rate alert crossings, by rank and sli")


class SloTracker:
    """Per-runtime SLI accumulator: hot-path feeders append, the cold
    evaluator scans.  ``clock`` is injectable for deterministic burn
    tests."""

    __slots__ = ("_ops", "_cycles", "_t0", "_clock", "_lock",
                 "_ops_seen")

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        # Leaf lock shared by the feeders and window_stats: appending
        # while the evaluator iterates raises RuntimeError ("deque
        # mutated during iteration") — under sustained op traffic
        # that would fail nearly every evaluator tick, silencing burn
        # alerts exactly when the job is loaded.  Nothing is acquired
        # while holding it, so it can never participate in a cycle.
        self._lock = threading.Lock()
        self._ops = deque(maxlen=_MAX_OPS)
        self._cycles = deque(maxlen=_MAX_CYCLES)
        self._ops_seen = False
        self._t0 = clock()

    # -- hot feeders (O(1) append under the leaf lock) -----------------
    def note_op(self, n: int = 1):
        """``n`` collective ops completed (one fused response may
        complete many; gate on ENABLED at the site)."""
        with self._lock:
            self._ops.append((self._clock(), n))
            self._ops_seen = True

    def note_cycle(self, dt: float):
        """One controller cycle finished in ``dt`` seconds."""
        with self._lock:
            self._cycles.append((self._clock(), dt))

    # -- cold reads ----------------------------------------------------
    def ops_seen(self) -> bool:
        """True once ANY op completion has ever been observed — the
        steps/s SLI's has-data gate.  Sticky on purpose: a window
        with zero ops after the first op is a genuine full stall and
        must be judged, but a job still in JIT compile / warmup that
        has never completed an op has produced no data to judge."""
        return self._ops_seen

    def uptime(self) -> float:
        return max(1e-6, self._clock() - self._t0)

    def window_stats(self, window_s: float) -> Dict[str, float]:
        """Achieved SLI values over the trailing ``window_s`` seconds.
        The window is clamped to uptime so a fresh tracker is judged
        only on the time it has actually lived (no startup burn)."""
        now = self._clock()
        span = min(window_s, self.uptime())
        cutoff = now - span
        # Half-open trailing window (cutoff, now]: a sample sitting
        # exactly on the boundary belongs to the previous window.
        # The scan holds the feeder lock — iteration breaks at the
        # window edge, so the hold is proportional to the window's
        # sample count, not the retention caps.
        ops = 0
        cyc_n = 0
        cyc_sum = 0.0
        with self._lock:
            for t, n in reversed(self._ops):
                if t <= cutoff:
                    break
                ops += n
            for t, dt in reversed(self._cycles):
                if t <= cutoff:
                    break
                cyc_n += 1
                cyc_sum += dt
        return {
            "span_s": span,
            "ops": float(ops),
            "steps_per_s": ops / span,
            "cycle_seconds": (cyc_sum / cyc_n) if cyc_n else 0.0,
            "cycles": float(cyc_n),
        }


def _shortfall(sli: str, achieved: float, target: float) -> float:
    """Normalized miss in [0, 1]: 0 = meeting target, 1 = total miss.
    steps/s is higher-is-better; cycle time is lower-is-better."""
    if target <= 0.0:
        return 0.0
    if sli == "steps_per_s":
        return min(1.0, max(0.0, 1.0 - achieved / target))
    # cycle_seconds: a cycle twice the target is a 100% miss.
    return min(1.0, max(0.0, achieved / target - 1.0))


class SloPlane:
    """The evaluator: owns the alert state machine and the ~1 Hz
    daemon thread; reads whichever tracker is registered."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tracker: Optional[SloTracker] = None
        self.rank: Optional[int] = None
        self._hook: Optional[Callable[[dict], None]] = None
        self._alerting: Dict[str, bool] = {}
        self._last_fire: Dict[str, float] = {}
        self._alert_counts: Dict[str, int] = {}
        self._last_eval: Optional[dict] = None

    # -- lifecycle -----------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._eval_loop, name="hvd-slo", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _eval_loop(self):
        while not self._stop.wait(_EVAL_INTERVAL_S):
            try:
                self.evaluate()
            except Exception:
                logger.warning("slo evaluation failed", exc_info=True)

    # -- evaluation ----------------------------------------------------
    def evaluate(self) -> dict:
        """One evaluation tick: compute both windows for both SLIs,
        update alert state, fire side effects on crossings.  Safe to
        call directly (tests, slo_status on demand)."""
        cfg = _env.slo_targets()
        tracker = self.tracker
        out = {
            "enabled": True,
            "rank": self.rank,
            "targets": {"steps_per_s": cfg["steps_per_s"],
                        "cycle_seconds": cfg["cycle_seconds"]},
            "windows": {"short_s": cfg["window_short"],
                        "long_s": cfg["window_long"]},
            "budget": cfg["budget"],
            "burn_threshold": cfg["burn_threshold"],
            "slis": {},
            "alerts_total": dict(self._alert_counts),
        }
        if tracker is None:
            self._last_eval = out
            return out
        short = tracker.window_stats(cfg["window_short"])
        long_ = tracker.window_stats(cfg["window_long"])
        for sli, key in (("steps_per_s", "steps_per_s"),
                         ("cycle_seconds", "cycle_seconds")):
            target = cfg[key]
            # No-data gates: a cycle SLI with no cycles yet has
            # nothing to judge, and the steps SLI must not judge a
            # job that has never completed an op — JIT compile /
            # warmup can take minutes, and the uptime clamp only
            # fixes the rate denominator, not the no-data case.
            # ops_seen is sticky, so a zero-op window AFTER the
            # first op is a genuine full stall and IS judged.
            if sli == "steps_per_s":
                has_data = tracker.ops_seen()
            else:
                has_data = short["cycles"] > 0
            entry = {
                "target": target,
                "short": round(short[key], 6),
                "long": round(long_[key], 6),
                "has_data": has_data,
            }
            if target > 0.0:
                b_short = _shortfall(sli, short[key], target) \
                    / cfg["budget"] if has_data else 0.0
                b_long = _shortfall(sli, long_[key], target) \
                    / cfg["budget"] if has_data else 0.0
                entry["burn_short"] = round(b_short, 4)
                entry["burn_long"] = round(b_long, 4)
                alerting = (b_short >= cfg["burn_threshold"] and
                            b_long >= cfg["burn_threshold"])
                entry["alerting"] = alerting
                self._on_alert_state(sli, alerting, entry)
            out["slis"][sli] = entry
        out["alerts_total"] = dict(self._alert_counts)
        with self._lock:
            self._last_eval = out
        return out

    def _on_alert_state(self, sli: str, alerting: bool, entry: dict):
        now = time.monotonic()
        with self._lock:
            was = self._alerting.get(sli, False)
            self._alerting[sli] = alerting
            refire = alerting and \
                now - self._last_fire.get(sli, 0.0) >= _ALERT_REFIRE_S
            crossing = alerting and not was
            if crossing or refire:
                self._last_fire[sli] = now
        if not (crossing or refire):
            return
        if crossing:
            with self._lock:
                self._alert_counts[sli] = \
                    self._alert_counts.get(sli, 0) + 1
            _ALERTS.inc(1, rank=self.rank if self.rank is not None
                        else "unset", sli=sli)
            logger.warning(
                "SLO burn alert: %s achieving %s (target %s), burn "
                "short=%.2f long=%.2f", sli, entry["short"],
                entry["target"], entry["burn_short"],
                entry["burn_long"])
        if _fr.ENABLED:
            _fr.record(_fr.SLO_BURN, sli=sli, short=entry["short"],
                       long=entry["long"], target=entry["target"],
                       burn=entry["burn_short"])
        if _prof.ENABLED:
            _prof.trigger_capture(
                "slo_burn", "%s=%s target=%s burn=%.2f" % (
                    sli, entry["short"], entry["target"],
                    entry["burn_short"]))
        hook = self._hook
        if hook is not None:
            try:
                hook({"sli": sli, **entry})
            except Exception:
                logger.warning("slo burn hook failed", exc_info=True)

    # -- reads / publication ------------------------------------------
    def status(self) -> dict:
        with self._lock:
            last = self._last_eval
        if last is None:
            return self.evaluate()
        return last

    def signals_reading(self) -> Dict[str, Optional[float]]:
        """The tuple ElasticPolicy.Signals consumes: short-window
        achieved values.  None means the SLI has no samples yet;
        an achieved 0.0 steps/s with samples is a real full-stall
        reading — the most actionable one — and is reported as 0.0,
        never collapsed into no-data by truthiness."""
        st = self.status()
        slis = st.get("slis", {})
        steps_e = slis.get("steps_per_s", {})
        cyc_e = slis.get("cycle_seconds", {})
        return {
            "steps_per_s": steps_e.get("short")
            if steps_e.get("has_data") else None,
            "cycle_time_s": cyc_e.get("short")
            if cyc_e.get("has_data") else None,
        }

    def publish(self, rank: int):
        """Fold the last evaluation into rank-labeled gauges so the
        next MR reply carries them (each rank writes only its OWN
        label — the relay MA pre-aggregation survival contract)."""
        self.rank = rank
        st = self.status()
        for sli, gauge in (("steps_per_s", _STEPS),
                           ("cycle_seconds", _CYCLE)):
            entry = st.get("slis", {}).get(sli)
            if not entry:
                continue
            gauge.set(entry["short"], rank=rank, window="short")
            gauge.set(entry["long"], rank=rank, window="long")
            for window in ("short", "long"):
                burn = entry.get("burn_%s" % window)
                if burn is not None:
                    _BURN.set(burn, rank=rank, sli=sli, window=window)


# ---------------------------------------------------------------------------
# module-level lifecycle
# ---------------------------------------------------------------------------

_PLANE: Optional[SloPlane] = None


def configure(enabled: bool = True,
              clock: Callable[[], float] = time.monotonic):
    """(Re)arm the SLO plane: creates a fresh tracker + evaluator
    thread.  ``clock`` is injectable for deterministic tests."""
    global ENABLED, _PLANE
    if not enabled:
        reset()
        return
    if _PLANE is not None:
        _PLANE.stop()
    _PLANE = SloPlane()
    _PLANE.tracker = SloTracker(clock=clock)
    _PLANE.start()
    ENABLED = True
    logger.debug("slo plane armed")


def reset():
    """Disable the plane and stop its evaluator thread."""
    global ENABLED, _PLANE
    ENABLED = False
    if _PLANE is not None:
        _PLANE.stop()
        _PLANE = None


def plane() -> Optional[SloPlane]:
    return _PLANE


def tracker() -> Optional[SloTracker]:
    """The hot-path feeder handle: cache it once per runtime and gate
    every use on ``slo.ENABLED and tr is not None``."""
    p = _PLANE
    return p.tracker if p is not None else None


def set_rank(rank: int):
    p = _PLANE
    if p is not None:
        p.rank = rank


def set_burn_hook(fn: Optional[Callable[[dict], None]]):
    """Install the alert side-channel (rank 0 wires a rendezvous KV
    publisher; drills wire an event recorder)."""
    p = _PLANE
    if p is not None:
        p._hook = fn


def publish(rank: int):
    """Feeder site for the MR-reply path; gate on ENABLED there."""
    p = _PLANE
    if p is not None:
        p.publish(rank)


def slo_status() -> dict:
    """The ``hvd.slo_status()`` payload; self-describing when off."""
    p = _PLANE
    if p is None:
        return {"enabled": False}
    return p.status()


def signals_reading() -> Dict[str, Optional[float]]:
    p = _PLANE
    if p is None:
        return {"steps_per_s": None, "cycle_time_s": None}
    return p.signals_reading()


def slo_from_snapshot(snap: dict) -> Dict[int, dict]:
    """Extract ``{rank: {sli: {window: value}, burn: {...}}}`` from a
    metrics snapshot (MR reply / relay MA aggregate / merged cluster
    view) — the digest_from_snapshot shape for the SLO gauges."""
    out: Dict[int, dict] = {}
    gauges = snap.get("gauges", {}) if isinstance(snap, dict) else {}
    for metric, field in (("hvd_slo_steps_per_s", "steps_per_s"),
                          ("hvd_slo_cycle_seconds", "cycle_seconds")):
        children = gauges.get(metric)
        if not isinstance(children, dict):
            continue
        for key, value in children.items():
            labels = dict(item.split("=", 1)
                          for item in key.split(",") if "=" in item)
            try:
                rank = int(labels["rank"])
                window = labels["window"]
            except (KeyError, ValueError):
                continue
            out.setdefault(rank, {}).setdefault(
                field, {})[window] = float(value)
    children = gauges.get("hvd_slo_burn_rate")
    if isinstance(children, dict):
        for key, value in children.items():
            labels = dict(item.split("=", 1)
                          for item in key.split(",") if "=" in item)
            try:
                rank = int(labels["rank"])
            except (KeyError, ValueError):
                continue
            out.setdefault(rank, {}).setdefault("burn", {})[
                "%s.%s" % (labels.get("sli", "?"),
                           labels.get("window", "?"))] = float(value)
    return out


# Arm from the environment at import (the HOROVOD_FAILPOINTS
# precedent: the knob rides the launcher env contract to every rank).
if _env.env_bool(_env.HOROVOD_SLO):
    configure(enabled=True)
