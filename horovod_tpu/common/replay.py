"""Steady-state replay: negotiation-free execution of converged cycles.

Training loops are overwhelmingly steady-state: after warm-up every
step submits the same tensors in the same order — the property PyTorch
DDP exploits with static-graph bucketing and negotiation skipping
(Li et al., VLDB '20, PAPERS.md).  The response-cache fast path already
detects this (every submission is a CH bit, every response a CB batch)
but still pays one coordinator round-trip per op: the measured tiny-op
floor (BENCH_r05: 0.435 ms median) is that round trip.

This module removes it.  Each rank tracks its own submission stream
against the CB frames it receives.  A *cycle* is the span between two
submissions of the same leading tensor; a cycle is *converged* when
every response in it arrived as a CB batch (pure cache-bit round) and
its ordered (key, signature) sequence and batch split match the
previous cycle.  After ``HOROVOD_REPLAY_WARMUP_CYCLES`` consecutive
converged cycles the rank freezes the fused response schedule and
enters REPLAY: subsequent submissions are matched against the frozen
schedule and executed directly — no CH frame, no CB wait, no wire
traffic at all.

Why rank-local entry is safe: CB/RS frames are broadcast identically
to every rank, and every rank submits the same ordered stream (the
same-graphs contract all of Horovod's negotiation rests on), so all
ranks count the same converged cycles and flip into replay at the same
logical step.  That argument additionally requires the loop to be
*synchronous at the cycle boundary* (every response delivered before
the next step's first submission — true for any loop that waits on
its handles each step, since observation precedes delivery): a
program holding async handles ACROSS the boundary would make each
cycle's convergence verdict a per-rank race.  The tracker therefore
(a) permanently disables itself the first time a clean cycle's
deliveries fail to cover its submissions (the signature of
cross-boundary pipelining, impossible in a boundary-synchronous
loop), and (b) never lets recv-thread timing touch tracking state:
frame-side disruptions (process-set or error traffic; EV/PA) act
through a monotonic op-index floor (``_void_before``) — the frame's
position in the broadcast stream, identical on every rank — rather
than by flagging "the current cycle", which is a different cycle on
different ranks.  Cycle verdicts compare that floor against the
cycle's start index (both content-deterministic), and entry
re-validates the whole stable window against the floor, which is
fully up to date by then because frames are processed in order and
the submitter blocks on the window's final response.
Should engagement ever diverge anyway, the failure is bounded, not
silent: the replaying rank's data-plane op times out (ring exchange
timeout) and the negotiating peer is attributed by the coordinator
stall machinery.  The wire format is untouched and the coordinator
(C++ or Python) needs no changes — during full replay it simply sees
no frames.

Exit conditions (any of these falls back to a normal negotiation
round, results bit-identical either way because replay executes the
very same merged Response objects the CB path built):

* an unseen tensor or a changed signature (new graph / shape change);
* a cache eviction (EV) touching a scheduled bit, or autotuned
  parameter (PA) frames;
* any RS/CB frame while replaying (defensive: a peer negotiated);
* a grouped submission, join, barrier, alltoall, or process-set
  change;
* an armed failpoint (``failpoints.ENABLED``) — fault-injection runs
  must exercise the negotiated path;
* shutdown / a broken control plane.

While an autotune-then-freeze search is live (horovod_tpu/tune), the
tracker additionally HOLDS entry — counted under
``hvd_steady_state_exits{reason="tuning"}`` — and engages only after
the freeze/abort announcement releases it (``set_tuning``); tuning and
replay are phases of one lifecycle, not mutually exclusive modes
(docs/autotune.md).

Known limitation: a rank joining EARLY (uneven data) cannot signal
peers mid-replay — their next replayed collective fails with a
bounded data-plane timeout instead of zero-substituting (see
docs/steady_state_replay.md; same restriction as DDP static_graph +
join).  Simultaneous joins are fine: each rank exits at its own join
submission.

Only ALLREDUCE / ADASUM / BROADCAST cycles are replayable: for those,
cross-rank signature agreement is enforced by negotiation itself
(mismatch is a validated ERROR), so one rank exiting on a signature
change implies every rank exits at the same step.  ALLGATHER and
REDUCESCATTER legally vary dim 0 per rank, which would let one rank
renegotiate while another replays a stale size vector — cycles
containing them never stabilize.

Observability: ``hvd_steady_state_entries`` / ``hvd_steady_state_exits``
(labeled by reason) / ``hvd_steady_state_cycles_replayed`` counters,
plus REPLAY_ENTER / REPLAY_EXIT timeline instants.  Replayed
submissions are recorded with the local stall inspector exactly like
negotiated ones, so a rank wedged mid-batch still attributes.
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import logging
import threading
from typing import List, Optional, Tuple

from . import failpoints as _fp
from . import flight_recorder as _fr
from . import metrics
from .message import Request, RequestType, Response, ResponseType
from .response_cache import request_signature

logger = logging.getLogger("horovod_tpu.replay")

_ENTRIES = metrics.counter(
    "hvd_steady_state_entries",
    "Times a rank froze a converged cycle and entered replay")
_EXITS = metrics.counter(
    "hvd_steady_state_exits",
    "Replay exits back into negotiation, by reason")
_CYCLES_REPLAYED = metrics.counter(
    "hvd_steady_state_cycles_replayed",
    "Full cycles executed from the frozen schedule (no wire traffic)")

# Request types whose cross-rank signature agreement is enforced by
# negotiation (see module docstring) — the only ones replay may freeze.
REPLAYABLE = {RequestType.ALLREDUCE, RequestType.ADASUM,
              RequestType.BROADCAST}
_TRACKED_RESPONSES = {ResponseType.ALLREDUCE, ResponseType.ADASUM,
                      ResponseType.BROADCAST}

# Failpoint sites whose effect is NOT bypassed by replay: they fire
# on the submitting thread BEFORE replay handling (runtime.submit is
# evaluated at the top of BackgroundRuntime.submit), so a schedule
# armed ONLY at these sites keeps its full effect under a frozen
# schedule and must not pin the negotiated path.  The straggler drills
# depend on this: a failpoint-delayed rank stays slow while replay
# stays engaged (docs/steady_state_replay.md).  Any other armed site
# still pins negotiation — fault schedules normally target the wire
# sites replay bypasses, and silently skipping them would report a
# vacuous pass.
REPLAY_SAFE_SITES = frozenset({"runtime.submit"})

# A cycle that never closes (auto-named tensors — every unnamed eager
# op gets a fresh "<op>.noname.<n>" key, so no leading key ever
# repeats) would otherwise accumulate tracking state forever.  Past
# this many ops without a boundary the tracker voids and re-anchors,
# bounding memory; the cap is far above any real per-step tensor
# count, and the trigger position is in the submission stream, so
# every rank resets at the same point.
MAX_CYCLE_OPS = 4096


class _Batch:
    """One frozen fused execution: the ordered keys this rank submits,
    their signatures, the merged Response to execute, and the cache
    bits backing it (for EV intersection)."""

    __slots__ = ("keys", "sigs", "response", "bits")

    def __init__(self, keys, sigs, response: Response, bits):
        self.keys: Tuple[tuple, ...] = tuple(keys)
        self.sigs: Tuple[tuple, ...] = tuple(sigs)
        self.response = response
        self.bits = frozenset(bits)


class SteadyStateReplay:
    """Per-rank tracker + frozen-schedule executor (one per
    BackgroundRuntime; created only for the networked controller)."""

    def __init__(self, runtime, warmup_cycles: int = 3,
                 enabled: bool = True):
        self.runtime = runtime
        self.warmup = max(1, int(warmup_cycles))
        self.enabled = enabled
        self._lock = threading.RLock()
        # Orders frozen-batch executions by match order even if several
        # submitter threads race (acquired under _lock, held across the
        # data-plane call, released after).
        self._exec_lock = threading.Lock()
        self.active = False
        # --- tracking state (inactive mode) ---
        self._cycle: List[Tuple[tuple, tuple]] = []   # [(key, sig)]
        self._delivered: List[tuple] = []  # [(kind, keys, resp, bits)]
        self._prev_cycle = None            # (keys, sigs, batch_split)
        self._last_delivered = None        # batches of last clean cycle
        self._stable = 0
        # Monotonic op-index counters, aligned 1:1 in a boundary-
        # synchronous loop: every tracked submission is matched by one
        # tracked delivery before the next cycle begins.  Disruptions
        # void convergence through _void_before — an op-index floor
        # below which no cycle may count — rather than by flagging
        # "the current cycle", because WHICH cycle is current when a
        # frame is processed is recv-thread timing, different per
        # rank, while the frame's position in the broadcast stream
        # (and so the op-index floor it sets) is identical everywhere.
        self._subs_seen = 0       # tracked submissions observed
        self._ops_delivered = 0   # tracked-response ops delivered
        self._void_before = 0     # cycles starting below this: void
        self._cycle_start = 0     # _subs_seen at current cycle start
        self._window_start = 0    # cycle_start of the stable streak
        # --- replay state (active mode) ---
        self._schedule: List[_Batch] = []
        self._sched_bits = frozenset()
        self._pos = 0
        self._batch_reqs: List[Request] = []
        self._disabled_reason: Optional[str] = None
        # Autotune-then-freeze hold (horovod_tpu/tune): while a tuning
        # session is searching, knob proposals (PA frames) re-shape
        # fused batches mid-stream, so a frozen schedule would go
        # stale the moment the next proposal lands.  The tracker keeps
        # OBSERVING cycles but refuses entry, counting each suppressed
        # entry under hvd_steady_state_exits{reason="tuning"}; the
        # freeze/abort announcement releases the hold (set_tuning) and
        # replay then engages cleanly on the tuned schedule.  This
        # replaces the old blanket autotune-disables-replay exclusion.
        self._tuning = False
        # Cached replay-safe verdict for the current failpoint rule
        # set (see REPLAY_SAFE_SITES): re-derived only when the
        # failpoint config generation changes, so the hot path never
        # takes the failpoint registry lock.
        self._fp_gen = -1
        self._fp_pins = True

    # ------------------------------------------------------------------
    # submission-side hooks (called from BackgroundRuntime.submit)
    # ------------------------------------------------------------------
    @staticmethod
    def _key(req: Request) -> tuple:
        return (req.process_set_id, req.tensor_name)

    def eligible(self, req: Request) -> bool:
        # Global-world collectives only: process-set members and
        # non-members see DIFFERENT submission streams for the same
        # CB broadcasts, so members would converge while non-members
        # never do — divergent engagement deadlocks the first global
        # tensor after entry.  A ps collective anywhere in the cycle
        # keeps every rank on the negotiated path (non-members via
        # the delivery-side check in on_responses).
        return req.group_id < 0 and req.process_set_id == 0 and \
            not req.process_set_ranks and \
            req.request_type in REPLAYABLE

    def observe_submit(self, req: Request) -> bool:
        """Track one eligible submission (inactive mode).  Returns True
        when this submission is the boundary at which replay engages —
        the caller must then route it through :meth:`replay_submit`."""
        if not self.enabled:
            return False
        key, sig = self._key(req), request_signature(req)
        with self._lock:
            if self.active:       # raced an entry on another thread
                return True
            if self._cycle and key == self._cycle[0][0]:
                self._close_cycle_locked()
                if self._stable >= self.warmup and \
                        self._try_enter_locked():
                    return True
            if len(self._cycle) >= MAX_CYCLE_OPS:
                self._void_before = self._subs_seen
                self._reset_tracking_locked()
            if not self._cycle:
                self._cycle_start = self._subs_seen
            self._cycle.append((key, sig))
            self._subs_seen += 1
            return False

    def replay_submit(self, req: Request, entry) -> bool:
        """Active mode: match ``req`` against the frozen schedule and
        execute the batch when complete.  Returns False when replay
        exited instead — the caller falls through to the normal
        negotiation path with this request untouched."""
        to_exec: Optional[Response] = None
        names: Tuple[str, ...] = ()
        with self._lock:
            if not self.active:
                return False
            if _fp.ENABLED and self._failpoints_pin_locked():
                # Armed failpoints pin the negotiated path: fault
                # schedules target the wire sites replay bypasses.
                # Replay-safe schedules (REPLAY_SAFE_SITES only) keep
                # their effect under replay and don't exit.
                self._exit_locked("failpoint")
                return False
            key, sig = self._key(req), request_signature(req)
            batch = self._schedule[self._pos]
            idx = len(self._batch_reqs)
            if idx >= len(batch.keys) or batch.keys[idx] != key:
                self._exit_locked("unseen_tensor")
                return False
            if batch.sigs[idx] != sig:
                self._exit_locked("signature_change")
                return False
            runtime = self.runtime
            # Entry lands in the table first (the error/flush machinery
            # must be able to fail it); a duplicate name is the same
            # programming error it is on the negotiated path.
            runtime.tensor_queue.add_entry_only(entry)
            if runtime.stall_inspector is not None:
                runtime.stall_inspector.record_uncached_tensor(
                    req.tensor_name, req.request_rank)
            if runtime.timeline:
                # _perform_operation closes one span per name; open it
                # as REPLAY so the trace shows which ops skipped
                # negotiation.
                runtime.timeline.negotiate_start(req.tensor_name,
                                                 "REPLAY")
            self._batch_reqs.append(req)
            if len(self._batch_reqs) == len(batch.keys):
                self._batch_reqs = []
                self._pos += 1
                if self._pos >= len(self._schedule):
                    self._pos = 0
                    _CYCLES_REPLAYED.inc()
                to_exec = batch.response
                names = batch.keys
                # Acquired under _lock: executions happen in match
                # order even with racing submitter threads.
                self._exec_lock.acquire()
        if to_exec is not None:
            try:
                self.runtime.replay_execute(to_exec)
            finally:
                self._exec_lock.release()
        return True

    def _failpoints_pin_locked(self) -> bool:
        """True when the armed failpoint schedule targets any site
        replay would bypass (caller holds self._lock and has already
        seen _fp.ENABLED).  The verdict is cached per failpoint config
        generation — re-derived on configure()/reset(), never on the
        per-op path."""
        gen = _fp.CONFIG_GEN
        if gen != self._fp_gen:
            self._fp_gen = gen
            self._fp_pins = any(site not in REPLAY_SAFE_SITES
                                for site in _fp.sites())
        return self._fp_pins

    def note_disruption(self, reason: str):
        """A non-replayable event in the submission stream (group,
        join, barrier, alltoall, process-set change): exits replay if
        active, else resets convergence tracking.  These fire at
        submission-stream positions — content-deterministic under the
        same-graphs contract — so a full reset (fresh anchor at the
        next submission) is identical on every rank."""
        with self._lock:
            if self.active:
                self._exit_locked(reason)
            else:
                self._void_before = self._subs_seen
                self._reset_tracking_locked()

    # ------------------------------------------------------------------
    # controller-side hooks (called from the recv thread)
    # ------------------------------------------------------------------
    def on_responses(self, kind: str, delivered: List[tuple]):
        """``kind`` is "cb" or "rs"; ``delivered`` is a list of
        (response, bits) in broadcast order (bits empty for RS)."""
        with self._lock:
            if self.active:
                # Defensive: during full replay the coordinator is
                # silent; any response frame means some rank negotiated
                # — fall back before executing it.  Alltoall frames
                # get their own exit label: per-step-varying splits
                # are the EXPECTED steady-state-breaking traffic of
                # the sparse/DLRM workload, and lumping them under the
                # generic reason hides whether an exit storm is the
                # embedding exchange (by design) or a genuinely
                # diverged peer.
                reason = "alltoall" if any(
                    r.response_type == ResponseType.ALLTOALL
                    for r, _ in delivered) else "frame_during_replay"
                self._exit_locked(reason)
                return
            if not self.enabled:
                return  # dormant: don't accumulate delivery history
            for resp, bits in delivered:
                tracked = resp.response_type in _TRACKED_RESPONSES \
                    and not resp.error_message \
                    and resp.process_set_id == 0 \
                    and not resp.process_set_ranks
                if not tracked:
                    # Process-set / error / barrier-class traffic:
                    # its position relative to the LOCAL cycle is
                    # recv-thread timing, so flagging "the current
                    # cycle" would void cycle N on one rank and N+1 on
                    # another (divergent convergence counts = wedge).
                    # Raise the op-index floor instead: the frame's
                    # position in the broadcast stream — hence the
                    # floor value — is identical on every rank, and
                    # _close/_try_enter apply it deterministically.
                    self._void_before = max(self._void_before,
                                            self._ops_delivered)
                    continue
                if not self._cycle:
                    # No cycle in progress: a joined rank (receives
                    # every broadcast, never submits, so no boundary
                    # would ever drain this list) or a pipelined loop
                    # (the cover check at its next boundary fails and
                    # disables replay).  Either way, don't accumulate.
                    continue
                keys = tuple((resp.process_set_id, n)
                             for n in resp.tensor_names)
                self._delivered.append((kind, keys, resp, tuple(bits)))
                self._ops_delivered += len(keys)

    def on_evictions(self, bits):
        with self._lock:
            if self.active and self._sched_bits & set(bits):
                self._exit_locked("eviction")
            # Inactive: deliberately a no-op.  The evicted tensor's
            # next submission renegotiates (an RS round), and that RS
            # breaks convergence deterministically via the all-CB
            # check in _close_cycle_locked; acting on the EV frame
            # itself would tie tracking state to recv-thread timing
            # (see on_responses).  A schedule frozen just before the
            # EV is still correct — replay executes stored Responses
            # and never consults the cache, and the bit set only
            # feeds the active-mode exit above.

    def on_params(self):
        """PA frame observed.  Recv-thread timing, so the inactive
        case acts through the op-index floor exactly like the
        non-tracked traffic in on_responses — a full reset here would
        void cycle N on one rank and N+1 on another (this path was
        dead before autotune-then-freeze: PA frames used to imply
        replay was disabled outright, so nothing ever tracked while
        one arrived)."""
        with self._lock:
            if self.active:
                self._exit_locked("params")
            else:
                self._void_before = max(self._void_before,
                                        self._ops_delivered)

    def on_broken(self):
        self.note_disruption("broken")

    # ------------------------------------------------------------------
    # lifecycle / test controls
    # ------------------------------------------------------------------
    def set_tuning(self, active: bool):
        """Hold (True) or release (False) replay entry for the tuning
        lifecycle.  The release arrives as a PA frame — ordered in
        the broadcast stream but PROCESSED at recv-thread timing — so
        it must never reset tracking directly (which cycle is current
        differs per rank); it acts through the op-index floor instead:
        the post-freeze convergence window is required to start at or
        after the release's stream position, identical on every rank,
        and entry under the tuned knobs happens at the same cycle
        boundary everywhere.  The hold itself is armed before any
        traffic (runtime init), where a reset is position-free."""
        with self._lock:
            if bool(active) == self._tuning:
                return
            self._tuning = bool(active)
            if self.active:
                # Entry raced the announcement on another thread; the
                # exit flushes any partial batch back to negotiation.
                self._exit_locked("tuning")
            elif active:
                self._reset_tracking_locked()
            else:
                self._void_before = max(self._void_before,
                                        self._ops_delivered)

    def set_warmup(self, cycles: int):
        """Adopt a tuned replay-warmup knob (takes effect at the next
        convergence streak; announced via PA, so identical on every
        rank at the same stream position)."""
        with self._lock:
            self.warmup = max(1, int(cycles))

    def set_enabled(self, flag: bool):
        """Runtime toggle (bench lanes measure the negotiated floor by
        disabling replay, then re-enable it for the replay floor)."""
        with self._lock:
            self.enabled = bool(flag)
            if flag:
                self._disabled_reason = None
            else:
                if self.active:
                    self._exit_locked("disabled")
                else:
                    self._reset_tracking_locked()

    def stats(self) -> dict:
        with self._lock:
            return {"active": self.active,
                    "stable_cycles": self._stable,
                    "schedule_batches": len(self._schedule),
                    "tuning_hold": self._tuning,
                    "disabled_reason": self._disabled_reason}

    # ------------------------------------------------------------------
    # internals (caller holds self._lock)
    # ------------------------------------------------------------------
    def _close_cycle_locked(self):
        cycle, self._cycle = self._cycle, []
        delivered, self._delivered = self._delivered, []
        start = self._cycle_start
        if not cycle:
            self._stable = 0
            self._prev_cycle = None
            return
        if start < self._void_before:
            # A disruption (note_disruption, or non-tracked broadcast
            # traffic) landed at an op-index inside or after this
            # cycle's start: it cannot count.  The comparison is
            # between two content-deterministic indices, so every rank
            # reaches the same verdict for the same cycle no matter
            # when its recv thread processed the disrupting frame.
            self._stable = 0
            self._prev_cycle = None
            return
        # Converged iff the CB batches delivered since the cycle began
        # cover exactly the cycle's submissions, in order.
        flat = [k for _, keys, _, _ in delivered for k in keys]
        mixed = any(kind != "cb" for kind, _, _, _ in delivered)
        if flat != [k for k, _ in cycle] or mixed:
            self._stable = 0
            self._prev_cycle = None
            if not mixed:
                # A clean all-CB cycle whose deliveries do not cover
                # its submissions means a response was still in flight
                # at the boundary: the program pipelines submissions
                # ACROSS steps (async handles held over the boundary).
                # Whether a given rank wins that race is timing-local,
                # so convergence counting would diverge across ranks —
                # and divergent entry means one rank replays (silent)
                # while a peer negotiates (waiting for it): a wedge.
                # A synchronous-at-the-boundary program can never trip
                # this (the submitter is blocked until delivery, and
                # observation precedes delivery), so the first
                # observation proves the program is structurally
                # unsafe for replay: disable it for good.
                self.enabled = False
                self._disabled_reason = "async_overlap"
                logger.warning(
                    "steady-state replay disabled: submissions overlap"
                    " the cycle boundary (async handles held across"
                    " steps); replay requires boundary-synchronous"
                    " loops")
            return
        shape = (tuple(k for k, _ in cycle),
                 tuple(s for _, s in cycle),
                 tuple(len(keys) for _, keys, _, _ in delivered))
        if shape == self._prev_cycle and self._stable > 0 and \
                self._window_start >= self._void_before:
            self._stable += 1
        else:
            # Streak (re)starts here — including a continuing streak
            # whose window began below the floor (a disruption or
            # tuning release landed mid-streak): restarting at CLOSE
            # time keeps the anchor a pure function of content-
            # deterministic indices, so every rank restarts at the
            # same cycle no matter when its recv thread processed the
            # disrupting frame.
            self._prev_cycle = shape
            self._stable = 1
            self._window_start = start
        self._last_delivered = delivered

    def _try_enter_locked(self) -> bool:
        if self._tuning:
            # A tuning search is live: refuse entry, touching NO
            # tracking state — the release (a PA frame) lands at
            # recv-thread timing, so one rank may evaluate this
            # boundary held while a peer evaluates it released; both
            # must leave identical state behind (the released peer is
            # refused by the floor check below, which the release
            # raised) or their streaks diverge and one rank replays
            # while the other negotiates: a wedge (measured, not
            # hypothetical).  The label fires once per streak (stable
            # passes warmup exactly once while held, since nothing
            # resets it) so dashboards can tell "replay waiting on
            # the tuner" from a genuinely diverged workload.
            if self._stable == self.warmup:
                _EXITS.inc(1, reason="tuning")
                if _fr.ENABLED:
                    _fr.record(_fr.REPLAY,
                               rank=self.runtime.state.rank_info.rank,
                               phase="held", reason="tuning")
            return False
        if _fp.ENABLED and self._failpoints_pin_locked():
            # Armed failpoints pin the negotiated path (fault
            # schedules target the wire sites replay bypasses;
            # replay-safe schedules — REPLAY_SAFE_SITES only — keep
            # their effect under replay and don't pin).  Checked at
            # ENTRY, not only in replay_submit: otherwise a chaos run
            # would enter and immediately exit every warmup-K cycles,
            # inflating the entry/exit counters and spamming
            # REPLAY_ENTER/EXIT timeline instants forever.
            return False
        delivered = getattr(self, "_last_delivered", None)
        if not delivered:
            return False
        if self._window_start < self._void_before:
            # Retroactive validation: a disruption frame processed
            # AFTER some of the streak's cycles closed still voids
            # them here.  The recv thread processes frames in order
            # and the submitter blocks on the streak's final response,
            # so every frame preceding that response — anywhere a
            # disruption could hide — has been applied to
            # _void_before by the time entry is evaluated.  Pure
            # refusal, no state wipe: the NEXT cycle close restarts
            # the streak through the same window-vs-floor comparison
            # (_close_cycle_locked), at the same content-deterministic
            # position on every rank — wiping here would interleave
            # with the recv-timed tuning-hold check above and anchor
            # different ranks at different cycles.
            return False
        # Signatures are taken POSITIONALLY from the converged cycle:
        # _close_cycle_locked proved the delivered keys equal the
        # cycle's keys in order, and a cycle may legally contain the
        # same tensor name twice with different signatures (sequential
        # reuse) — a name-keyed lookup would freeze only the last one.
        sigs = self._prev_cycle[1]
        schedule, pos = [], 0
        for kind, keys, resp, bits in delivered:
            schedule.append(_Batch(
                keys, sigs[pos:pos + len(keys)], resp, bits))
            pos += len(keys)
        self._schedule = schedule
        self._sched_bits = frozenset(
            b for batch in schedule for b in batch.bits)
        self._pos = 0
        self._batch_reqs = []
        self.active = True
        _ENTRIES.inc()
        if _fr.ENABLED:
            _fr.record(_fr.REPLAY,
                       rank=self.runtime.state.rank_info.rank,
                       phase="enter", batches=len(schedule))
        if self.runtime.timeline:
            self.runtime.timeline.instant("REPLAY_ENTER")
        logger.debug("steady-state replay engaged: %d batches, %d "
                     "tensors/cycle", len(schedule),
                     sum(len(b.keys) for b in schedule))
        return True

    def _exit_locked(self, reason: str):
        if not self.active:
            return
        self.active = False
        _EXITS.inc(1, reason=reason)
        if _fr.ENABLED:
            _fr.record(_fr.REPLAY,
                       rank=self.runtime.state.rank_info.rank,
                       phase="exit", reason=reason)
        if self.runtime.timeline:
            self.runtime.timeline.instant("REPLAY_EXIT_" + reason)
        logger.debug("steady-state replay exited: %s", reason)
        # A partially-submitted batch falls back to negotiation: its
        # entries are already in the table, so only the requests need
        # to reach the coordinator.  Every rank exits at the same
        # stream position (same-graphs contract), so peers queue the
        # same requests and the round completes normally.
        reqs, self._batch_reqs = self._batch_reqs, []
        if reqs:
            self.runtime.tensor_queue.queue_requests(reqs)
            self.runtime.wake()
        self._reset_tracking_locked()

    def _reset_tracking_locked(self):
        # Callers sit at content-deterministic stream positions
        # (submission-side disruptions, replay exits, explicit
        # disable), so the fresh anchor at the next submission is the
        # same key on every rank.  Recv-thread-timed events (EV/PA,
        # process-set traffic) must NOT call this — they act through
        # the _void_before op-index floor instead (see on_responses).
        # The monotonic counters are deliberately preserved: the
        # floor semantics depend on op indices never restarting.
        self._cycle = []
        self._delivered = []
        self._prev_cycle = None
        self._last_delivered = None
        self._stable = 0
        self._schedule = []
        self._sched_bits = frozenset()
        self._pos = 0
