"""Failpoints: process-wide deterministic fault injection.

An etcd/TiKV-style failpoint registry: production code declares named
injection *sites* at the places that can fail for real (ring transport
dispatch, coordinator frame I/O, the runtime cycle, rendezvous KV
requests, elastic worker lifecycle, the liveness/reconnect plane:
``net.heartbeat_drop``/``net.conn_drop``/``net.half_open``/
``worker.wedge``); an operator or test configures
*rules* against those sites through ``HOROVOD_FAILPOINTS``::

    HOROVOD_FAILPOINTS='ring.send=delay(50ms,p=0.1);coord.frame_recv=drop(1);
                        elastic.worker=crash(rank=3,epoch=2)'

Grammar (``;``-separated rules, several rules may target one site)::

    rule    := site "=" action "(" args? ")"
    action  := delay | drop | error | crash | partition
    args    := arg ("," arg)*          # positional first, then k=v

Actions (positional argument in brackets):

* ``delay([duration])`` — sleep for the duration (default 50ms) at the
  site, then continue.
* ``drop([times])`` — ask the site to discard the unit of work (a
  frame, an HTTP request).  A bare count is shorthand for ``times=N``.
* ``error([message])`` — raise :class:`FailpointError` at the site.
* ``crash()`` — invoke the process crash handler (default
  ``os._exit(43)``; tests and the chaos harness override it with
  :func:`set_crash_handler`).  Sites that model *another* process's
  death (the elastic driver spawning workers) pass ``crash_ok=True``
  and interpret the returned ``"crash"`` themselves.
* ``partition([duration])`` — once triggered, EVERY evaluation of the
  site returns ``"drop"`` until the window (default 1s) elapses: a
  network partition rather than a single lost frame.

Shared predicates (all optional, all AND-ed):

* ``p=0.1`` — trigger with that probability, drawn from the rule's own
  seeded PRNG (see below);
* ``times=N`` — trigger at most N times, then go inert;
* ``after=N`` — skip the first N otherwise-matching evaluations;
* ``rank=R`` — only on that rank (the caller's ``rank=`` context wins,
  else the rank installed by ``hvd.init``, else ``HOROVOD_RANK``);
* ``epoch=E`` — only in that elastic epoch (caller context, else the
  worker's rendezvoused epoch).

Determinism: every rule owns a ``random.Random`` seeded from
``(HOROVOD_FAILPOINTS_SEED, site, action, rule index)``, so a schedule
replays identically for a fixed seed regardless of which other sites
fire — the property the chaos soak harness builds its reproducible
fault schedules on.

Zero overhead when disabled: sites are written as

    if failpoints.ENABLED and failpoints.maybe_fail("site") == "drop":

so with ``HOROVOD_FAILPOINTS`` unset every site costs exactly one
module-attribute check (asserted by tests/test_failpoints.py).

Observability: triggers are counted per (site, action) into the PR-1
metrics registry (``hvd_failpoint_triggers_total``) and locally per
rule; :func:`snapshot` returns the per-site evaluation/trigger counts
the chaos soak embeds in its JSON artifact.
"""

import logging
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from . import env as _env
from . import flight_recorder as _fr
from . import metrics

logger = logging.getLogger("horovod_tpu.failpoints")

ENV_SPEC = "HOROVOD_FAILPOINTS"
ENV_SEED = "HOROVOD_FAILPOINTS_SEED"

ACTIONS = ("delay", "drop", "error", "crash", "partition")

# THE disabled-path gate: every site checks this one module attribute
# before anything else.  configure()/reset() are the only writers.
ENABLED = False

# Bumped on every configure()/reset(): consumers that derive cached
# state from the rule set (steady-state replay's replay-safe-site
# check) re-derive when it changes instead of taking the registry lock
# on every hot-path evaluation.
CONFIG_GEN = 0

_TRIGGERS = metrics.counter(
    "hvd_failpoint_triggers_total",
    "Failpoint rules fired, by site and action")

_lock = threading.Lock()
_rules: Dict[str, List["_Rule"]] = {}
_seed: int = 0
_rank: Optional[int] = None          # installed by hvd.init / tests
_epoch_provider: Optional[Callable[[], int]] = None


class FailpointError(RuntimeError):
    """Raised at a site by an ``error(...)`` rule."""


def _default_crash(site: str):
    logger.error("failpoint %s: injected crash (os._exit)", site)
    os._exit(43)


_crash_handler: Callable[[str], None] = _default_crash


def set_crash_handler(fn: Optional[Callable[[str], None]]):
    """Override what ``crash()`` does (None restores ``os._exit``).
    The chaos harness and the unit tests install raising handlers so a
    crash can be simulated inside one process."""
    global _crash_handler
    _crash_handler = fn if fn is not None else _default_crash


def set_rank(rank: Optional[int]):
    """Install the current rank for ``rank=`` predicates (wired from
    ``hvd.init``; call-site ``rank=`` context still wins)."""
    global _rank
    _rank = rank


def _current_rank() -> Optional[int]:
    if _rank is not None:
        return _rank
    return _env.env_int_opt(_env.HOROVOD_RANK)


def _current_epoch() -> int:
    if _epoch_provider is not None:
        try:
            return int(_epoch_provider())
        except Exception:
            return 0
    try:
        from ..runner.elastic.worker import current_epoch
        return current_epoch()
    except Exception:
        return 0


def set_epoch_provider(fn: Optional[Callable[[], int]]):
    global _epoch_provider
    _epoch_provider = fn


def _parse_duration(text: str) -> float:
    """``50ms`` / ``2s`` / ``100us`` / bare seconds float."""
    t = text.strip().lower()
    for suffix, mult in (("us", 1e-6), ("ms", 1e-3), ("s", 1.0)):
        if t.endswith(suffix):
            return float(t[:-len(suffix)]) * mult
    return float(t)


# Per-action meaning of the single allowed positional argument.
_POSITIONAL = {
    "delay": ("duration", _parse_duration),
    "partition": ("duration", _parse_duration),
    "drop": ("times", int),
    "error": ("message", str),
    "crash": ("times", int),
}

_PREDICATE_KEYS = {
    "p": float, "times": int, "after": int, "rank": int, "epoch": int,
    "duration": _parse_duration, "message": str,
}


class _Rule:
    __slots__ = ("site", "action", "p", "times", "after", "rank",
                 "epoch", "duration", "message", "_rng", "_evals",
                 "_triggers", "_partition_until")

    def __init__(self, site: str, action: str, args: Dict[str, object],
                 seed: int, index: int):
        self.site = site
        self.action = action
        self.p = float(args.get("p", 1.0))
        self.times = args.get("times")
        self.after = int(args.get("after", 0))
        self.rank = args.get("rank")
        self.epoch = args.get("epoch")
        self.duration = args.get(
            "duration", 0.05 if action == "delay" else 1.0)
        self.message = args.get("message") or \
            "failpoint %s: injected error" % site
        # Independent per-rule stream: which OTHER rules fire (and how
        # often this site is hit) never perturbs this rule's draws
        # beyond the draw count at the site itself.
        self._rng = random.Random("%d|%s|%s|%d"
                                  % (seed, site, action, index))
        self._evals = 0
        self._triggers = 0
        self._partition_until = 0.0

    def evaluate(self, rank: Optional[int], epoch: Optional[int]):
        """One evaluation under the registry lock; returns
        ``(outcome, fresh)`` when this rule fires (behavior is applied
        by the caller, outside the lock) — ``fresh`` is False for
        units swallowed by an already-open partition window — or None.
        """
        if self.rank is not None:
            r = rank if rank is not None else _current_rank()
            if r != self.rank:
                return None
        if self.epoch is not None:
            e = epoch if epoch is not None else _current_epoch()
            if e != self.epoch:
                return None
        if self.action == "partition" and \
                time.monotonic() < self._partition_until:
            # Open window swallows everything; NOT a fresh trigger —
            # metrics/logging count rule firings, not swallowed units.
            return ("drop", False)
        self._evals += 1
        if self._evals <= self.after:
            return None
        if self.times is not None and self._triggers >= int(self.times):
            return None
        if self.p < 1.0 and self._rng.random() >= self.p:
            return None
        self._triggers += 1
        if self.action == "partition":
            self._partition_until = time.monotonic() + self.duration
            return ("drop", True)
        return (self.action, True)


def _parse_rule(text: str, seed: int, index: int) -> _Rule:
    site, sep, rest = text.partition("=")
    site, rest = site.strip(), rest.strip()
    if not sep or not site:
        raise ValueError("failpoint rule %r: expected site=action(...)"
                         % text)
    name, paren, argtext = rest.partition("(")
    name = name.strip()
    if name not in ACTIONS:
        raise ValueError("failpoint rule %r: unknown action %r "
                         "(expected one of %s)"
                         % (text, name, "/".join(ACTIONS)))
    if paren:
        argtext = argtext.rstrip()
        if not argtext.endswith(")"):
            raise ValueError("failpoint rule %r: unbalanced parens"
                             % text)
        argtext = argtext[:-1]
    args: Dict[str, object] = {}
    for part in argtext.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, value = part.partition("=")
        if eq:
            key, value = key.strip(), value.strip()
            if key not in _PREDICATE_KEYS:
                raise ValueError(
                    "failpoint rule %r: unknown argument %r" % (text, key))
            args[key] = _PREDICATE_KEYS[key](value)
        else:
            pos_key, conv = _POSITIONAL[name]
            if pos_key in args:
                raise ValueError("failpoint rule %r: duplicate "
                                 "positional argument" % text)
            args[pos_key] = conv(part)
    return _Rule(site, name, args, seed, index)


def configure(spec: str, seed: Optional[int] = None) -> int:
    """(Re)build the registry from a spec string.  Returns the number
    of rules installed; an empty spec disables the subsystem.  Raises
    ValueError on malformed rules (a typo'd schedule silently injecting
    nothing would defeat the whole point)."""
    global ENABLED, _seed, _rules
    if seed is None:
        seed = _env.env_int(ENV_SEED, 0)
    rules: Dict[str, List[_Rule]] = {}
    count = 0
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        rule = _parse_rule(part, seed, count)
        rules.setdefault(rule.site, []).append(rule)
        count += 1
    global CONFIG_GEN
    with _lock:
        _seed = seed
        _rules = rules
        ENABLED = bool(rules)
        CONFIG_GEN += 1
    if rules:
        logger.info("failpoints enabled (seed=%d): %s", seed,
                    "; ".join("%s=%s" % (r.site, r.action)
                              for rs in rules.values() for r in rs))
    return count


def reset():
    """Disable the subsystem and drop all rules/counters."""
    global ENABLED, _rules, CONFIG_GEN
    with _lock:
        _rules = {}
        ENABLED = False
        CONFIG_GEN += 1


def maybe_fail(site: str, rank: Optional[int] = None,
               epoch: Optional[int] = None,
               crash_ok: bool = False) -> Optional[str]:
    """Evaluate the rules for ``site``; the first firing rule wins.

    Side effects by action: ``delay`` sleeps here; ``error`` raises
    :class:`FailpointError`; ``crash`` invokes the crash handler
    (unless ``crash_ok``, where the caller models the death itself).
    Returns the fired action name (``partition`` surfaces as
    ``"drop"``) or None.  Callers ignore outcomes that make no sense
    for their site — only ``"drop"`` requires cooperation.
    """
    with _lock:
        rules = _rules.get(site)
        if not rules:
            return None
        fired = None
        for rule in rules:
            result = rule.evaluate(rank, epoch)
            if result is not None:
                fired = (rule,) + result
                break
    if fired is None:
        return None
    rule, outcome, fresh = fired
    if fresh:
        _TRIGGERS.inc(1, site=site, action=rule.action)
        if _fr.ENABLED:
            # The chaos schedule in causal position: a postmortem can
            # show the injected fault BETWEEN the frames it perturbed.
            _fr.record(_fr.FAILPOINT, rank=rank, site=site,
                       action=rule.action)
        logger.debug("failpoint %s: %s fired (trigger #%d)", site,
                     rule.action, rule._triggers)
    if outcome == "delay":
        time.sleep(rule.duration)
    elif outcome == "error":
        raise FailpointError(rule.message)
    elif outcome == "crash" and not crash_ok:
        _crash_handler(site)
    return outcome


def sites() -> List[str]:
    with _lock:
        return sorted(_rules)


def snapshot() -> dict:
    """Per-site rule state: evaluations and triggers, for artifacts."""
    with _lock:
        return {
            site: [{"action": r.action, "evals": r._evals,
                    "triggers": r._triggers,
                    "p": r.p, "times": r.times, "after": r.after,
                    "rank": r.rank, "epoch": r.epoch}
                   for r in rules]
            for site, rules in _rules.items()
        }


# Arm from the environment at import: the spec rides the launcher env
# contract to every worker, so a single HOROVOD_FAILPOINTS on the
# driver arms the whole job.
_env_spec = _env.env_str_opt(ENV_SPEC)
if _env_spec:
    configure(_env_spec)
