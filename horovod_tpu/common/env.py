"""Environment-variable knob and rank contract parsing.

The single source of truth for configuration is environment variables, the
same contract the reference core uses (reference: common/common.h:64-92,
parsed in operations.cc:441-534 and utils/env_parser.cc; rank identity
contract in runner/gloo_run.py:65-76).  The launcher translates CLI flags /
YAML config into these variables and forwards them to every slot; the
in-process runtime reads them once at ``init()``.
"""

import dataclasses
import os
from typing import Optional

# --- rank identity contract (set by the launcher for every slot) ---------
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"

# --- rendezvous / control plane ------------------------------------------
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_IFACE = "HOROVOD_GLOO_IFACE"
# Elastic workers ask the rendezvous server for a fresh rank assignment
# using this scope key (reference: gloo/gloo_context.cc:154-200).
GET_RANK_AND_SIZE = "rank_and_size"

# --- performance knobs ----------------------------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
# Steady-state replay: after N converged cache-hit cycles a rank
# freezes the fused response schedule and executes it locally with no
# coordinator round-trips (common/replay.py).  On by default; 0/false
# disables.
HOROVOD_STEADY_STATE_REPLAY = "HOROVOD_STEADY_STATE_REPLAY"
HOROVOD_REPLAY_WARMUP_CYCLES = "HOROVOD_REPLAY_WARMUP_CYCLES"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
# Autotune-then-freeze (horovod_tpu/tune, docs/autotune.md): run an
# online knob search as replay's warmup phase — per-cycle-class fusion
# thresholds plus the worker knobs (cycle time, request coalescing,
# replay warmup) — then FREEZE the winner and let steady-state replay
# engage on the tuned schedule.  Python-coordinator-only (in-line round
# scoring + PA knob frames), the same gating as HOROVOD_AUTOTUNE.
HOROVOD_TUNE = "HOROVOD_TUNE"
# Tuned-profile artifact path: while tuning, the freeze persists the
# winning configuration here; at init, an EXISTING valid profile is
# loaded instead of re-searching (restarts and elastic resizes skip
# straight to the frozen knobs + replay).
HOROVOD_TUNE_PROFILE = "HOROVOD_TUNE_PROFILE"
# Search strategy: "gp" (Gaussian-process Expected Improvement over
# the continuous knobs, fixed seed) or "grid" (deterministic
# coordinate descent — what tests and chaos drills pin).
HOROVOD_TUNE_STRATEGY = "HOROVOD_TUNE_STRATEGY"
HOROVOD_TUNE_CYCLES_PER_SAMPLE = "HOROVOD_TUNE_CYCLES_PER_SAMPLE"
HOROVOD_TUNE_MAX_SAMPLES = "HOROVOD_TUNE_MAX_SAMPLES"
HOROVOD_TUNE_WARMUP_WINDOWS = "HOROVOD_TUNE_WARMUP_WINDOWS"
# Request coalescing (PR 4): the inline fast path is taken only from
# an IDLE tensor table, so async bursts drain as one CH/RQ frame per
# kind.  On by default; the tuner explores both settings (0 = every
# submission goes inline immediately, one frame per op).
HOROVOD_REQUEST_COALESCING = "HOROVOD_REQUEST_COALESCING"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"

# --- robustness / self-healing control plane -----------------------------
# Bounds how long workers wait for each other at init: the controller
# connect loop, the rendezvous addr lookup, the elastic re-rendezvous
# and the coordinator drain all derive their deadline from this one
# knob (launcher --start-timeout; reference launch.py start_timeout
# contract).  Historically each site re-read the variable with its own
# default; `start_timeout()` is now the single parse point.
HOROVOD_START_TIMEOUT = "HOROVOD_START_TIMEOUT"
START_TIMEOUT_DEFAULT = 120.0
# Control-plane liveness: when the interval is > 0, every worker sends
# lightweight HB heartbeat frames on its coordinator link (suppressed
# while real traffic flows) and the coordinator runs a liveness sweep,
# so a wedged-but-connected rank (SIGSTOP, GIL deadlock, half-open
# socket) is detected within ~2x the interval even with no collective
# pending.  0 (default) = disabled; liveness pins the Python
# coordinator (the native one has no HB handling — same gating as
# autotune/metrics aggregation/failpoints).
HOROVOD_LIVENESS_INTERVAL = "HOROVOD_LIVENESS_INTERVAL"
# Silence threshold before a peer is presumed dead.  Default (unset or
# 0): 2x the liveness interval.
HOROVOD_LIVENESS_TIMEOUT = "HOROVOD_LIVENESS_TIMEOUT"
# Reconnecting control channel: a worker whose coordinator socket dies
# retries with jittered exponential backoff inside this grace window,
# while the coordinator holds the rank in limbo and replays missed
# frames on resume — a transient TCP drop no longer breaks the world.
# Default (unset or 0 with liveness enabled): the liveness timeout;
# explicit 0 with liveness disabled = reconnects off (legacy fail-fast
# behavior).
HOROVOD_RECONNECT_GRACE = "HOROVOD_RECONNECT_GRACE"
# Bound on the registration-phase first frame: a client that connects
# and never identifies its rank is cut after this many seconds
# (previously a hardcoded 30 s).
HOROVOD_REGISTRATION_TIMEOUT = "HOROVOD_REGISTRATION_TIMEOUT"
# Relay-tree control plane (docs/architecture.md): interior relay
# nodes — one per simulated "host", arity bounded by this knob —
# aggregate their children's CH/RQ/MQ uplinks and fan CB/RS/HB
# broadcasts down, so rank 0 touches O(fanout) links instead of
# O(world).  0 (default) = the flat star (every rank links directly
# to rank 0, byte-identical to the pre-tree wire behavior); > 0 pins
# the Python coordinator (the native one has no relay frames — same
# gating as liveness/autotune/metrics aggregation).  Worlds of
# size <= fanout + 1 stay flat even when set (a relay would not
# reduce the root's link count).
HOROVOD_COORD_FANOUT = "HOROVOD_COORD_FANOUT"
# Relay address map for launchers/harnesses that pre-assign relay
# ports: a JSON object {"<relay_id>": "host:port", ...}.  When set,
# workers resolve relay addresses from it and NO rank self-hosts a
# relay (the harness owns them); when unset, designated host ranks
# start relays in-process and publish their addresses through the
# rendezvous KV (key ``relay.<id>`` in the controller scope).
HOROVOD_RELAY_ADDRS = "HOROVOD_RELAY_ADDRS"
# Depth-aware liveness deadlines: every relay hop adds forwarding
# latency (and, during a re-home, up to one grace window) between a
# peer's heartbeat and its observer, so a depth-blind timeout would
# false-promote healthy subtrees behind a busy relay.  The effective
# deadline a node applies to a link grows linearly with the number of
# relay hops the watched traffic crosses:
#
#     effective_timeout(base, hops) = base * (1 + HOP_SLACK * hops)
#
# hops = 0 is a direct link (flat star and the root's leaf links —
# exactly the pre-tree behavior); a leaf at depth d watches the
# coordinator through d relay hops; the root watches a relay link
# with the subtree's depth below it.  The detection-bound table by
# depth lives in docs/failure_recovery.md.
LIVENESS_HOP_SLACK = 0.5


def depth_aware_liveness_timeout(base_timeout_s: float,
                                 hops: int) -> float:
    """Effective liveness deadline for a link whose watched traffic
    crosses ``hops`` relay hops (see LIVENESS_HOP_SLACK above for the
    formula; hops=0 reproduces the flat-star deadline exactly)."""
    return base_timeout_s * (1.0 + LIVENESS_HOP_SLACK * max(0, int(hops)))
# Differential checkpoints: the longest base→tip delta chain before
# the manager forces the next save to be a full base (bounds restore
# replay cost and the blast radius of a corrupt base).  0 = deltas
# disabled (every save is a full base).
HOROVOD_CKPT_DELTA_CHAIN_MAX = "HOROVOD_CKPT_DELTA_CHAIN_MAX"
CKPT_DELTA_CHAIN_MAX_DEFAULT = 8


def ckpt_delta_chain_max() -> int:
    """The delta-chain length bound, parsed freshly on every call
    (bench lanes and drills sweep it per phase)."""
    return max(0, env_int(HOROVOD_CKPT_DELTA_CHAIN_MAX,
                          CKPT_DELTA_CHAIN_MAX_DEFAULT))


# --- sparse lookup plane --------------------------------------------------
# Dedupe repeated ids within a batch before the ids alltoall in
# ShardedEmbedding.lookup (each unique id crosses the wire once; rows
# scatter back through the inverse index).  On Zipf-shaped traffic this
# cuts alltoall bytes hard; 0 disables for A/B measurement.
HOROVOD_SPARSE_DEDUPE = "HOROVOD_SPARSE_DEDUPE"


def sparse_dedupe_enabled() -> bool:
    """Whether lookup dedupes ids before the exchange, parsed freshly
    per lookup (the bytes-comparison test flips it between passes)."""
    return env_bool(HOROVOD_SPARSE_DEDUPE, True)


# --- online serving plane (horovod_tpu/serve/) ----------------------------
# Staleness bound for serving reads: reject a lookup when the freshest
# committed training step is more than this many steps ahead of the
# snapshot the replica is serving.  0 = unbounded (never reject).
HOROVOD_SERVE_MAX_STALENESS_STEPS = "HOROVOD_SERVE_MAX_STALENESS_STEPS"
SERVE_MAX_STALENESS_STEPS_DEFAULT = 0
# How often the replica's tail thread polls the checkpoint directory
# for newly committed manifests (seconds).
HOROVOD_SERVE_POLL_SECONDS = "HOROVOD_SERVE_POLL_SECONDS"
SERVE_POLL_SECONDS_DEFAULT = 0.5
# Port for the HTTP lookup endpoint (0 = ephemeral).
HOROVOD_SERVE_PORT = "HOROVOD_SERVE_PORT"


def serve_max_staleness_steps() -> int:
    """The staleness-rejection bound in steps (0 = unbounded), parsed
    freshly per lookup so tests and operators can tighten it live."""
    return max(0, env_int(HOROVOD_SERVE_MAX_STALENESS_STEPS,
                          SERVE_MAX_STALENESS_STEPS_DEFAULT))


def serve_poll_seconds() -> float:
    """The manifest-tail poll interval in seconds."""
    return max(0.01, env_float(HOROVOD_SERVE_POLL_SECONDS,
                               SERVE_POLL_SECONDS_DEFAULT))


def start_timeout(default: float = None) -> float:
    """The HOROVOD_START_TIMEOUT deadline (seconds), parsed freshly on
    every call so tests and elastic re-inits that mutate the env see
    the current value."""
    return env_float(HOROVOD_START_TIMEOUT,
                     START_TIMEOUT_DEFAULT if default is None else default)


# --- observability --------------------------------------------------------
# Black-box flight recorder (common/flight_recorder.py): a bounded
# in-memory ring of typed control-plane events, dumped as per-rank
# JSON on failure triggers (lost-rank promotion, stall shutdown, fatal
# unwind, SIGUSR2) and merged into one causal chrome-trace +
# machine-readable verdict by tools/blackbox_merge.py.
# HOROVOD_BLACKBOX=1 arms the ring (extract via SIGUSR2 or the
# /blackbox HTTP handler); HOROVOD_BLACKBOX_DIR=/path also enables the
# automatic failure-trigger dumps; HOROVOD_BLACKBOX_EVENTS bounds the
# ring (default 8192 events).  Disabled cost on the submit/frame hot
# paths is ONE attribute check (the failpoints precedent, pinned by
# tests/test_flight_recorder.py).
HOROVOD_BLACKBOX = "HOROVOD_BLACKBOX"
HOROVOD_BLACKBOX_DIR = "HOROVOD_BLACKBOX_DIR"
HOROVOD_BLACKBOX_EVENTS = "HOROVOD_BLACKBOX_EVENTS"
# Live straggler observatory (common/straggler.py): per-cycle
# critical-path attribution on the coordinator (which rank's readiness
# arrived last, folded into per-rank lag EWMAs), per-rank phase
# summaries riding the MR metrics frames so attribution keeps working
# during steady-state replay, hvd_straggler_score{rank} gauges, and
# the /status plane + tools/hvdtop.py dashboard.  HOROVOD_STRAGGLER=1
# arms it; disabled cost on the submit/recv hot paths is ONE attribute
# check (the failpoints/flight-recorder precedent, pinned by
# tests/test_straggler.py).
HOROVOD_STRAGGLER = "HOROVOD_STRAGGLER"
# A rank whose normalized lag score crosses this threshold is flagged
# slow: one flight-recorder event + an elastic/slow/<rank> rendezvous
# KV notice (the pre-emptive-migration hook, ROADMAP item 5c).
HOROVOD_STRAGGLER_THRESHOLD = "HOROVOD_STRAGGLER_THRESHOLD"
STRAGGLER_THRESHOLD_DEFAULT = 4.0
# Noise floor (seconds): arrival-lag / peer-wait gaps below this never
# score — a tight world full of microsecond jitter must read all-zero.
HOROVOD_STRAGGLER_MIN_LAG = "HOROVOD_STRAGGLER_MIN_LAG"
STRAGGLER_MIN_LAG_DEFAULT = 0.005


def straggler_threshold() -> float:
    """Score threshold for flagging a rank slow, parsed freshly (the
    drills sweep it per phase)."""
    return env_float(HOROVOD_STRAGGLER_THRESHOLD,
                     STRAGGLER_THRESHOLD_DEFAULT)


def straggler_min_lag() -> float:
    """The attribution noise floor in seconds (see above)."""
    return max(1e-4, env_float(HOROVOD_STRAGGLER_MIN_LAG,
                               STRAGGLER_MIN_LAG_DEFAULT))


# Continuous sampling profiler (common/profiler.py): a low-Hz
# sys._current_frames walker per rank that attributes wall time to
# subsystem lanes (submit/controller/ring/replay/checkpoint), ships a
# top-K hot-frame digest on the MR metrics frames, serves the full
# collapsed-stack profile at job-secret GET /profile, and snapshots
# the last window when a straggler flag / stall warning / SLO burn
# fires.  HOROVOD_PROFILE=1 arms it; disabled cost on hot paths is ONE
# attribute check (the failpoints precedent, pinned by
# tests/test_profiler.py).
HOROVOD_PROFILE = "HOROVOD_PROFILE"
# Sampling frequency in Hz.  10 Hz resolves anything that dominates a
# multi-second window while staying ~0.1% overhead; drills bump it to
# sharpen time-to-root-cause.
HOROVOD_PROFILE_HZ = "HOROVOD_PROFILE_HZ"
PROFILE_HZ_DEFAULT = 10.0
# Digest width: how many hot frames each rank folds into its MR reply.
HOROVOD_PROFILE_TOPK = "HOROVOD_PROFILE_TOPK"
PROFILE_TOPK_DEFAULT = 5


def profile_hz() -> float:
    """Profiler sampling frequency, parsed freshly (drills sweep it
    per phase); clamped to [0.1, 250] Hz."""
    return min(250.0, max(0.1, env_float(HOROVOD_PROFILE_HZ,
                                         PROFILE_HZ_DEFAULT)))


def profile_topk() -> int:
    """Hot-frame digest width (entries per rank per MR reply)."""
    return max(1, env_int(HOROVOD_PROFILE_TOPK, PROFILE_TOPK_DEFAULT))


# SLO plane (common/slo.py): steps/s and cycle-time SLIs over short /
# long sliding windows with multi-window burn-rate alerting (the SRE
# fast+slow window pattern: an alert fires only when BOTH windows burn
# error budget faster than the threshold, killing both flap and
# blindness).  HOROVOD_SLO=1 arms it; targets of 0 disable their SLI.
HOROVOD_SLO = "HOROVOD_SLO"
# Throughput target: completed collective ops per second (the
# hvd_worker_op_rate vocabulary).  0 (default) = SLI off.
HOROVOD_SLO_STEPS_PER_S = "HOROVOD_SLO_STEPS_PER_S"
SLO_STEPS_PER_S_DEFAULT = 0.0
# Latency target: controller cycle seconds (matches
# hvd_controller_cycle_seconds).  0 (default) = SLI off.
HOROVOD_SLO_CYCLE_SECONDS = "HOROVOD_SLO_CYCLE_SECONDS"
SLO_CYCLE_SECONDS_DEFAULT = 0.0
# Sliding-window lengths (seconds): short catches fast regressions,
# long confirms they are sustained.
HOROVOD_SLO_WINDOW_SHORT = "HOROVOD_SLO_WINDOW_SHORT"
SLO_WINDOW_SHORT_DEFAULT = 30.0
HOROVOD_SLO_WINDOW_LONG = "HOROVOD_SLO_WINDOW_LONG"
SLO_WINDOW_LONG_DEFAULT = 300.0
# Burn-rate alert threshold: alert when shortfall/budget >= this in
# BOTH windows (2.0 = burning monthly budget at 2x sustainable rate).
HOROVOD_SLO_BURN_THRESHOLD = "HOROVOD_SLO_BURN_THRESHOLD"
SLO_BURN_THRESHOLD_DEFAULT = 2.0
# Error budget: tolerated fractional shortfall against the target
# (0.1 = achieving 90% of target consumes budget at exactly 1x).
HOROVOD_SLO_BUDGET = "HOROVOD_SLO_BUDGET"
SLO_BUDGET_DEFAULT = 0.1


def slo_targets() -> dict:
    """SLO targets + window/burn config, parsed freshly per
    evaluation tick (drills sweep targets to force burns)."""
    return {
        "steps_per_s": max(0.0, env_float(HOROVOD_SLO_STEPS_PER_S,
                                          SLO_STEPS_PER_S_DEFAULT)),
        "cycle_seconds": max(0.0, env_float(
            HOROVOD_SLO_CYCLE_SECONDS, SLO_CYCLE_SECONDS_DEFAULT)),
        "window_short": max(1.0, env_float(HOROVOD_SLO_WINDOW_SHORT,
                                           SLO_WINDOW_SHORT_DEFAULT)),
        "window_long": max(1.0, env_float(HOROVOD_SLO_WINDOW_LONG,
                                          SLO_WINDOW_LONG_DEFAULT)),
        "burn_threshold": max(0.1, env_float(
            HOROVOD_SLO_BURN_THRESHOLD, SLO_BURN_THRESHOLD_DEFAULT)),
        "budget": min(1.0, max(1e-4, env_float(HOROVOD_SLO_BUDGET,
                                               SLO_BUDGET_DEFAULT))),
    }


HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
# Opt-in Prometheus-text /metrics endpoint: set to a port (0 = pick an
# ephemeral one); unset = no endpoint.  Each rank binds
# port + local_rank so one knob serves multi-rank hosts.
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
# Cross-rank metrics aggregation cadence (seconds): the rank-0
# coordinator polls per-rank snapshots over the control plane at this
# interval.  0 (default) = disabled; setting it opts into the Python
# coordinator (the native one has no metrics frames).
HOROVOD_METRICS_AGG_SECONDS = "HOROVOD_METRICS_AGG_SECONDS"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"

# --- elastic --------------------------------------------------------------
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_HOSTNAME_KEY = HOROVOD_HOSTNAME
# Closed-loop elasticity (runner/elastic, docs/failure_recovery.md
# "Autoscaling").  Scale-up admission: when enabled (default), hosts
# discovered AFTER the initial formation are admitted mid-job — the
# driver holds them pending until the policy engine approves, then
# bumps the discovery generation so workers re-rendezvous into the
# grown world.  Disabled: discovered hosts still serve as replacements
# at the next failure-driven replan, but never trigger a resize on
# their own.
HOROVOD_ELASTIC_SCALE_UP = "HOROVOD_ELASTIC_SCALE_UP"
# Blacklist cooldown (seconds): a host evicted for a failure is
# re-admitted after base * 2^(strikes-1) seconds (decaying
# re-admission — each repeat offense doubles the sit-out, capped at
# 2^6 ≈ 64x).  0 (default) = permanent blacklist (legacy behavior).
HOROVOD_ELASTIC_BLACKLIST_COOLDOWN = "HOROVOD_ELASTIC_BLACKLIST_COOLDOWN"
BLACKLIST_COOLDOWN_DEFAULT = 0.0
BLACKLIST_MAX_STRIKE_DOUBLINGS = 6
# Bound on one --host-discovery-script execution: a hung script times
# out after this many seconds, the driver logs ONCE and keeps the
# last-good host set (the start_timeout()-style fresh-parse contract).
HOROVOD_ELASTIC_DISCOVERY_TIMEOUT = "HOROVOD_ELASTIC_DISCOVERY_TIMEOUT"
DISCOVERY_TIMEOUT_DEFAULT = 10.0
# Policy engine (runner/elastic/policy.py): resize decisions from the
# aggregated signals (pending hosts, straggler scores, cycle time /
# queue depth / steps-per-s) instead of only from deaths.  WINDOW is
# the hysteresis — a condition must hold for this many CONSECUTIVE
# observation ticks before a decision fires; COOLDOWN is the refractory
# period after any decision during which no new one fires (together
# they make flapping structurally impossible).
HOROVOD_ELASTIC_POLICY = "HOROVOD_ELASTIC_POLICY"
HOROVOD_ELASTIC_POLICY_WINDOW = "HOROVOD_ELASTIC_POLICY_WINDOW"
POLICY_WINDOW_DEFAULT = 3
HOROVOD_ELASTIC_POLICY_COOLDOWN = "HOROVOD_ELASTIC_POLICY_COOLDOWN"
POLICY_COOLDOWN_DEFAULT = 30.0
# Verdict-driven pre-emptive migration: act on the straggler
# observatory's elastic/slow-<rank> publications (slow-vs-dead: a rank
# with a ``lost`` notice is dead and owned by the eviction path; a
# ``slow`` notice means alive-but-lagging).  A rank persistently
# flagged for MIGRATE_AFTER seconds is checkpoint-then-evicted: the
# driver waits (bounded by MIGRATE_CKPT_WAIT) for ckpt/latest to
# advance past the decision point, then evicts the host BEFORE the
# stall clock would have fired.
HOROVOD_STRAGGLER_MIGRATE = "HOROVOD_STRAGGLER_MIGRATE"
HOROVOD_STRAGGLER_MIGRATE_AFTER = "HOROVOD_STRAGGLER_MIGRATE_AFTER"
STRAGGLER_MIGRATE_AFTER_DEFAULT = 10.0
HOROVOD_STRAGGLER_MIGRATE_CKPT_WAIT = "HOROVOD_STRAGGLER_MIGRATE_CKPT_WAIT"
STRAGGLER_MIGRATE_CKPT_WAIT_DEFAULT = 30.0


def elastic_scale_up_enabled() -> bool:
    """Mid-job scale-up admission gate, parsed freshly (drills and
    tests flip it per phase)."""
    return env_bool(HOROVOD_ELASTIC_SCALE_UP, True)


def blacklist_cooldown() -> float:
    """Base blacklist cooldown in seconds (0 = permanent), parsed
    freshly on every eviction."""
    return max(0.0, env_float(HOROVOD_ELASTIC_BLACKLIST_COOLDOWN,
                              BLACKLIST_COOLDOWN_DEFAULT))


def discovery_timeout() -> float:
    """Deadline for one host-discovery-script execution, seconds."""
    return max(0.1, env_float(HOROVOD_ELASTIC_DISCOVERY_TIMEOUT,
                              DISCOVERY_TIMEOUT_DEFAULT))


def policy_enabled() -> bool:
    """Policy-engine gate (default on: with it off, the driver falls
    back to the legacy react-only behavior — deaths shrink, discovery
    growth is admitted immediately with no hysteresis)."""
    return env_bool(HOROVOD_ELASTIC_POLICY, True)


def policy_window() -> int:
    """Hysteresis window: consecutive agreeing observation ticks
    required before the policy engine fires a decision."""
    return max(1, env_int(HOROVOD_ELASTIC_POLICY_WINDOW,
                          POLICY_WINDOW_DEFAULT))


def policy_cooldown() -> float:
    """Refractory period (seconds) after any resize decision."""
    return max(0.0, env_float(HOROVOD_ELASTIC_POLICY_COOLDOWN,
                              POLICY_COOLDOWN_DEFAULT))


def straggler_migrate_enabled() -> bool:
    """Pre-emptive straggler migration gate (default off: acting on
    scores is a policy choice, observing them is not)."""
    return env_bool(HOROVOD_STRAGGLER_MIGRATE, False)


def straggler_migrate_after() -> float:
    """Seconds a rank must stay flagged slow before the migration
    decision fires (persistence, not a single spike)."""
    return max(0.0, env_float(HOROVOD_STRAGGLER_MIGRATE_AFTER,
                              STRAGGLER_MIGRATE_AFTER_DEFAULT))


def straggler_migrate_ckpt_wait() -> float:
    """Bound on the checkpoint-then-evict wait for ckpt/latest to
    advance past the migration decision (seconds); expiry evicts
    anyway — step loss is then bounded by the checkpoint cadence."""
    return max(0.0, env_float(HOROVOD_STRAGGLER_MIGRATE_CKPT_WAIT,
                              STRAGGLER_MIGRATE_CKPT_WAIT_DEFAULT))

# --- TPU-specific ---------------------------------------------------------
HOROVOD_TPU_OPERATIONS = "HOROVOD_TPU_OPERATIONS"   # "XLA" (default) | "TCP"
HOROVOD_TPU_MESH_AXES = "HOROVOD_TPU_MESH_AXES"     # e.g. "dp:4,tp:2"
HOROVOD_TPU_COORDINATOR = "HOROVOD_TPU_COORDINATOR"  # jax.distributed addr

_TRUE = ("1", "true", "yes", "on")


def env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in _TRUE


def env_bool_opt(name: str):
    """Tri-state env bool: None when unset (lets the runtime pick a
    topology-dependent default)."""
    v = os.environ.get(name)
    if v is None:
        return None
    return v.strip().lower() in _TRUE


def env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


def env_int_opt(name: str):
    """Optional int knob: None when unset/empty/unparseable."""
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else None
    except ValueError:
        return None


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def env_str(name: str, default: str = "") -> str:
    v = os.environ.get(name)
    return v if v is not None else default


def env_str_opt(name: str) -> Optional[str]:
    """Optional string knob: None when unset (callers branch on
    presence — the tri-state analog of env_bool_opt)."""
    return os.environ.get(name)


def env_require(name: str) -> str:
    """A contract variable the launcher MUST have provided; a missing
    one raises KeyError(name) — the same failure mode as the direct
    ``os.environ[name]`` reads this accessor replaces."""
    return os.environ[name]


def env_set(name: str) -> bool:
    """Presence test (``name in os.environ``), without exposing the
    mapping to call sites."""
    return name in os.environ


@dataclasses.dataclass
class RankInfo:
    """The launcher → worker rank contract, or single-process defaults."""
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    @classmethod
    def from_env(cls) -> "RankInfo":
        return cls(
            rank=env_int(HOROVOD_RANK, 0),
            size=env_int(HOROVOD_SIZE, 1),
            local_rank=env_int(HOROVOD_LOCAL_RANK, 0),
            local_size=env_int(HOROVOD_LOCAL_SIZE, 1),
            cross_rank=env_int(HOROVOD_CROSS_RANK, 0),
            cross_size=env_int(HOROVOD_CROSS_SIZE, 1),
        )

    @property
    def launched(self) -> bool:
        """True when a launcher provided the contract (vs. bare script)."""
        return HOROVOD_RANK in os.environ


@dataclasses.dataclass
class Knobs:
    """Runtime tunables, parsed once at init.

    Defaults mirror the reference core's (operations.cc:441-534): 64 MB
    fusion threshold, 1 ms cycle time, 1024-entry response cache.  The
    autotuner may override fusion_threshold_bytes / cycle_time_ms at
    runtime (parameter manager).
    """
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    # None = auto: hierarchical allreduce defaults ON when each
    # process drives several chips (the all-local-chips layout; a flat
    # world-mesh eager op would idle all but one chip per host), OFF
    # for one-chip-per-process rigs. Explicit env/autotune settings
    # override (reference gates it behind HOROVOD_HIERARCHICAL_ALLREDUCE
    # unconditionally, operations.cc:441-534).
    hierarchical_allreduce: Optional[bool] = None
    hierarchical_allgather: bool = False
    autotune: bool = False
    replay_enabled: bool = True
    replay_warmup_cycles: int = 3
    # --- autotune-then-freeze (horovod_tpu/tune, docs/autotune.md) ---
    # tune_profile_loaded is derived, not an env knob: True when a
    # valid profile at tune_profile was applied onto these knobs, so
    # the runtime knows tuning is already frozen (replay engages
    # immediately; the coordinator runs no search).
    tune: bool = False
    tune_profile: Optional[str] = None
    tune_profile_loaded: bool = False
    # The parsed TunedProfile object when tune_profile_loaded: the
    # single read of the artifact (knob adoption AND the controller's
    # pre-frozen session both use it — re-reading the file later could
    # race a concurrent freeze replacing it).
    tune_profile_obj: Optional[object] = None
    tune_strategy: str = "gp"
    tune_cycles_per_sample: int = 8
    tune_max_samples: int = 24
    tune_warmup_windows: int = 2
    request_coalescing: bool = True
    autotune_log: Optional[str] = None
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    timeline: Optional[str] = None
    timeline_mark_cycles: bool = False
    metrics_port: Optional[int] = None
    metrics_agg_interval_s: float = 0.0
    stall_check_disable: bool = False
    stall_warning_time_s: float = 60.0
    stall_shutdown_time_s: float = 0.0
    elastic: bool = False
    tpu_operations: str = "XLA"
    # Self-healing control plane (docs/failure_recovery.md).
    # liveness_timeout_s / reconnect_grace_s may be given as 0 =
    # "derive the default"; __post_init__ resolves them ONCE for every
    # construction path (env, tests, chaos harness), so consumers read
    # final values.
    start_timeout_s: float = START_TIMEOUT_DEFAULT
    liveness_interval_s: float = 0.0   # 0 = liveness disabled
    liveness_timeout_s: float = 0.0    # 0 -> 2x interval
    reconnect_grace_s: float = 0.0     # 0 -> liveness timeout
    registration_timeout_s: float = 30.0
    coord_fanout: int = 0              # 0 = flat star (no relay tree)

    def __post_init__(self):
        if not self.liveness_timeout_s:
            self.liveness_timeout_s = 2.0 * self.liveness_interval_s
        if not self.reconnect_grace_s:
            self.reconnect_grace_s = self.liveness_timeout_s

    def apply_tuned_profile(self, profile) -> None:
        """Adopt a frozen tuned profile (horovod_tpu/tune) onto these
        knobs: the dense-class fusion threshold plus the worker knobs.
        Explicit env values are the profile's own starting point (the
        search anchored there), so profile-wins is the right order.
        Per-class thresholds for the coordinator come from the profile
        directly (controller_net builds a frozen session from it)."""
        dense = profile.fusion_bytes_for("dense")
        if dense:
            self.fusion_threshold_bytes = dense
        w = profile.worker or {}
        if "cycle_time_ms" in w:
            self.cycle_time_ms = float(w["cycle_time_ms"])
        if "coalesce" in w:
            self.request_coalescing = bool(w["coalesce"])
        if "replay_warmup" in w:
            self.replay_warmup_cycles = int(w["replay_warmup"])
        self.tune_profile_loaded = True

    @classmethod
    def from_env(cls) -> "Knobs":
        liveness_interval = env_float(HOROVOD_LIVENESS_INTERVAL, 0.0)
        liveness_timeout = env_float(HOROVOD_LIVENESS_TIMEOUT, 0.0)
        reconnect_grace = env_float(HOROVOD_RECONNECT_GRACE, 0.0)
        knobs = cls(
            fusion_threshold_bytes=env_int(
                HOROVOD_FUSION_THRESHOLD, 64 * 1024 * 1024),
            cycle_time_ms=env_float(HOROVOD_CYCLE_TIME, 1.0),
            cache_capacity=env_int(HOROVOD_CACHE_CAPACITY, 1024),
            hierarchical_allreduce=env_bool_opt(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=env_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            autotune=env_bool(HOROVOD_AUTOTUNE),
            replay_enabled=env_bool(HOROVOD_STEADY_STATE_REPLAY, True),
            replay_warmup_cycles=env_int(HOROVOD_REPLAY_WARMUP_CYCLES,
                                         3),
            tune=env_bool(HOROVOD_TUNE),
            tune_profile=os.environ.get(HOROVOD_TUNE_PROFILE),
            tune_strategy=os.environ.get(
                HOROVOD_TUNE_STRATEGY, "gp").strip().lower(),
            tune_cycles_per_sample=env_int(
                HOROVOD_TUNE_CYCLES_PER_SAMPLE, 8),
            tune_max_samples=env_int(HOROVOD_TUNE_MAX_SAMPLES, 24),
            tune_warmup_windows=env_int(
                HOROVOD_TUNE_WARMUP_WINDOWS, 2),
            request_coalescing=env_bool(
                HOROVOD_REQUEST_COALESCING, True),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG),
            autotune_warmup_samples=env_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steps_per_sample=env_int(
                HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
            autotune_bayes_opt_max_samples=env_int(
                HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20),
            autotune_gaussian_process_noise=env_float(
                HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8),
            timeline=os.environ.get(HOROVOD_TIMELINE),
            timeline_mark_cycles=env_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            metrics_port=env_int_opt(HOROVOD_METRICS_PORT),
            metrics_agg_interval_s=env_float(
                HOROVOD_METRICS_AGG_SECONDS, 0.0),
            stall_check_disable=env_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_time_s=env_float(
                HOROVOD_STALL_CHECK_TIME_SECONDS, 60.0),
            stall_shutdown_time_s=env_float(
                HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            elastic=env_bool(HOROVOD_ELASTIC),
            tpu_operations=os.environ.get(HOROVOD_TPU_OPERATIONS, "XLA"),
            start_timeout_s=start_timeout(),
            liveness_interval_s=liveness_interval,
            liveness_timeout_s=liveness_timeout,
            reconnect_grace_s=reconnect_grace,
            registration_timeout_s=env_float(
                HOROVOD_REGISTRATION_TIMEOUT, 30.0),
            coord_fanout=max(0, env_int(HOROVOD_COORD_FANOUT, 0)),
        )
        if knobs.tune_profile:
            # A valid frozen profile at the path means the search is
            # already done: adopt its knobs and skip straight to
            # replay.  A missing/corrupt file means "tune and write it
            # here" (try_load_profile is deliberately forgiving).
            from ..tune.profile import try_load_profile
            prof = try_load_profile(knobs.tune_profile)
            if prof is not None:
                knobs.apply_tuned_profile(prof)
                knobs.tune_profile_obj = prof
        return knobs
