"""Relay-tree control plane: interior fan-out nodes between the rank-0
coordinator and the leaf ranks (docs/architecture.md, ROADMAP item 1).

The flat star makes rank 0 do one serial send per rank on the hottest
broadcast path and hold one uplink socket per rank.  With
``HOROVOD_COORD_FANOUT=F`` the control plane becomes a tree instead:

* leaves (worker ranks) connect to a *relay* — one per simulated
  "host", arity <= F — speaking the regular wire format, unchanged;
* relays aggregate their children's uplink frames into batched ``RB``
  frames toward their parent and fan every broadcast frame down
  verbatim, so the root touches O(F) links and its recv loop drains
  batches instead of per-rank frames;
* relays themselves form a tree of arity <= F until <= F links reach
  the root.  Rank 0's own loopback client always connects directly.

Robustness by construction (the part that earns the hierarchy its
keep): relays are **stateless fail-stop forwarders**.  All per-rank
stream state — sessions, downlink out-logs, uplink cursors — stays on
the root, exactly where PR 6's reconnecting-channel machinery keeps
it.  A relay that dies (or loses its parent link) simply disappears:
its children see a dead socket and *re-home* — they walk their
ancestor chain (parent relay, grandparent, ..., root) with the
standard resume handshake, the root replays the downlink frames they
missed from their per-rank out-logs, and they replay their unacked
uplink frames.  A killed relay therefore costs one detection window,
never the world; children that cannot re-home inside the grace window
are promoted through the existing elastic eviction path.

Liveness composes per hop: every parent watches its children with the
depth-aware deadline (``env.depth_aware_liveness_timeout``), a relay
reports a silent/disconnected child up via an ``RL`` notice, and the
relay suppresses its children's idle heartbeats behind a single HB of
its own (HB/MR/MQ frames are *out-of-stream*: never logged, never
replayed — see controller_net).
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import heapq
import json
import logging
import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import env as env_mod
from . import flight_recorder as _fr
from . import metrics

logger = logging.getLogger("horovod_tpu.relay")

# --- relay-link frame kinds (relay<->parent hops only; the leaf<->
#     parent hop speaks the regular, byte-identical wire format) -------
MAGIC_RELAY_BATCH = b"RB"   # child->parent: batched uplink items
MAGIC_RELAY_DOWN = b"RD"    # parent->child relay: targeted downlink
MAGIC_RELAY_LOST = b"RL"    # relay->parent: child lost notice (JSON)
MAGIC_METRICS_AGG = b"MA"   # relay->parent: aggregated MR snapshots
MAGIC_REGISTER = b"RG"      # RB item kind: forwarded leaf registration

# Relay registration encodes the relay id in the (otherwise >= 0)
# registration rank field: relay k registers as rank -2 - k.  -1 is
# left unused (a sentinel in parts of the reference protocol).
_RELAY_REG_BASE = -2

_REHOMES = metrics.counter(
    "hvd_relay_rehomes_total",
    "Leaf re-home outcomes after a relay/link loss (resumed_parent = "
    "same relay came back; resumed_ancestor = climbed to a "
    "grandparent/the root; failed = grace window expired)")
_CHILD_LOST = metrics.counter(
    "hvd_relay_child_lost_total",
    "Children a relay reported lost to its parent, by kind")
_RELAY_FRAMES = metrics.counter(
    "hvd_relay_frames_total",
    "Frames forwarded through a relay, by direction")
_UPLINK_ITEMS = metrics.histogram(
    "hvd_relay_uplink_items_per_frame",
    "Child uplink items coalesced into one RB frame toward the "
    "parent (drain-all-pending batching)", bounds=metrics.COUNT_BUCKETS)
_AGG_SNAPSHOTS = metrics.counter(
    "hvd_relay_agg_metrics_total",
    "Aggregated MA metrics frames sent upward by relays (each "
    "replaces its subtree's individual MR replies)")
_SWEEP_VISITS = metrics.counter(
    "hvd_liveness_sweep_visits_total",
    "Deadline-heap entries visited by liveness sweeps (stays O(due), "
    "not O(world), per tick — asserted by the perf pin test)")


def relay_reg_rank(relay_id: int) -> int:
    return _RELAY_REG_BASE - relay_id


def is_relay_reg(rank: int) -> bool:
    return rank <= _RELAY_REG_BASE


def relay_id_from_reg(rank: int) -> int:
    return _RELAY_REG_BASE - rank


def relay_addr_map() -> Dict[int, str]:
    """The HOROVOD_RELAY_ADDRS map ({relay_id: "host:port"}), {} when
    unset/unparseable (the KV-published addresses then apply)."""
    raw = env_mod.env_str_opt(env_mod.HOROVOD_RELAY_ADDRS)
    if not raw:
        return {}
    try:
        return {int(k): str(v) for k, v in json.loads(raw).items()}
    except (ValueError, TypeError, AttributeError):
        logger.warning("unparseable %s=%r; ignoring",
                       env_mod.HOROVOD_RELAY_ADDRS, raw)
        return {}


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------

class RelayInfo:
    __slots__ = ("id", "level", "parent", "child_relays", "leaf_lo",
                 "leaf_hi")

    def __init__(self, rid, level, leaf_lo, leaf_hi):
        self.id = rid
        self.level = level
        self.parent: Optional[int] = None   # relay id; None = root
        self.child_relays: List[int] = []
        self.leaf_lo = leaf_lo   # leaf span [lo, hi) this subtree covers
        self.leaf_hi = leaf_hi

    @property
    def depth_below(self) -> int:
        """Relay hops from this node down to its leaves, counting the
        leaf link (level-0 relay -> 1)."""
        return self.level + 1

    @property
    def host_rank(self) -> int:
        """The worker rank that hosts this relay in launcher runs:
        the lowest rank of its span at every level (so one process
        hosts its whole ancestor column and parents come up with it)."""
        return self.leaf_lo


class TreePlan:
    """The deterministic relay tree for (size, fanout): every rank of
    1..size-1 is the direct child of exactly one level-0 relay; relays
    group under higher-level relays until <= fanout of them (plus rank
    0's direct link) reach the root."""

    def __init__(self, size: int, fanout: int):
        assert fanout > 0 and size - 1 > fanout
        self.size = size
        self.fanout = fanout
        self.relays: Dict[int, RelayInfo] = {}
        self._leaf_parent: Dict[int, int] = {}
        next_id = 0
        level_nodes: List[int] = []
        # Level 0: leaves 1..size-1 chunked by fanout.
        for lo in range(1, size, fanout):
            hi = min(size, lo + fanout)
            info = RelayInfo(next_id, 0, lo, hi)
            self.relays[next_id] = info
            for r in range(lo, hi):
                self._leaf_parent[r] = next_id
            level_nodes.append(next_id)
            next_id += 1
        # Higher levels until the top fits the root's fanout budget.
        level = 0
        while len(level_nodes) > fanout:
            level += 1
            parents: List[int] = []
            for i in range(0, len(level_nodes), fanout):
                chunk = level_nodes[i:i + fanout]
                info = RelayInfo(next_id, level,
                                 self.relays[chunk[0]].leaf_lo,
                                 self.relays[chunk[-1]].leaf_hi)
                info.child_relays = list(chunk)
                for c in chunk:
                    self.relays[c].parent = next_id
                self.relays[next_id] = info
                parents.append(next_id)
                next_id += 1
            level_nodes = parents
        self.root_relays: List[int] = list(level_nodes)
        self.levels = level + 1

    def leaf_parent(self, rank: int) -> Optional[int]:
        """Relay serving ``rank`` (None = direct root link; rank 0 is
        always direct)."""
        return self._leaf_parent.get(rank)

    def relay_ancestors(self, rid: int) -> List[int]:
        out = []
        cur = self.relays[rid].parent
        while cur is not None:
            out.append(cur)
            cur = self.relays[cur].parent
        return out

    def ancestors_of_leaf(self, rank: int) -> List[int]:
        """Relay chain from ``rank`` up to (excluding) the root,
        nearest first; [] for direct ranks."""
        rid = self.leaf_parent(rank)
        if rid is None:
            return []
        return [rid] + self.relay_ancestors(rid)

    def leaf_hops(self, rank: int) -> int:
        return len(self.ancestors_of_leaf(rank))

    def relays_hosted_by(self, rank: int) -> List[int]:
        """Relay ids this worker rank hosts in launcher runs, highest
        level first (parents must be up before children connect)."""
        out = [rid for rid, info in self.relays.items()
               if info.host_rank == rank]
        return sorted(out, key=lambda rid: -self.relays[rid].level)

    def to_meta(self) -> dict:
        return {"size": self.size, "fanout": self.fanout,
                "relays": len(self.relays), "levels": self.levels,
                "root_links": len(self.root_relays) + 1}


def plan_tree(size: int, fanout: int) -> Optional[TreePlan]:
    """The tree for (size, fanout); None when the flat star is the
    right topology (fanout off, or every rank fits the root's budget
    directly)."""
    if fanout <= 0 or size - 1 <= fanout:
        return None
    return TreePlan(size, fanout)


# ---------------------------------------------------------------------------
# lazy deadline heap (the O(due) liveness sweep)
# ---------------------------------------------------------------------------

class DeadlineHeap:
    """Min-heap of (deadline, key) with lazy revalidation: traffic on
    a link only updates its last-heard timestamp (O(1) dict store, no
    heap op); the sweep pops entries whose *recorded* deadline lapsed
    and re-schedules the ones whose true deadline moved.  A sweep tick
    therefore visits O(entries due) links, not O(world) — each live
    link costs one pop+push per timeout window, amortized, instead of
    one visit per tick."""

    def __init__(self):
        # Entries are (deadline, seq, key): the monotonic seq breaks
        # deadline ties so heapq never compares the keys themselves
        # (they are deliberately heterogeneous — ints, tuples, link
        # tokens — and unorderable).
        self._heap: List[Tuple[float, int, object]] = []
        self._seq = 0
        self.visits = 0   # popped entries, read by the perf pin test

    def __len__(self):
        return len(self._heap)

    def schedule(self, key, deadline: float):
        self._seq += 1
        heapq.heappush(self._heap, (deadline, self._seq, key))

    def due(self, now: float, deadline_fn) -> List[object]:
        """Pop lapsed entries; ``deadline_fn(key)`` returns the key's
        CURRENT true deadline or None (key no longer tracked).  Keys
        whose true deadline also lapsed are returned (and dropped —
        the caller re-schedules survivors it keeps); refreshed keys
        are re-pushed at their true deadline."""
        out = []
        while self._heap and self._heap[0][0] <= now:
            _, _, key = heapq.heappop(self._heap)
            self.visits += 1
            _SWEEP_VISITS.inc()
            true = deadline_fn(key)
            if true is None:
                continue
            if true <= now:
                out.append(key)
            else:
                self._seq += 1
                heapq.heappush(self._heap, (true, self._seq, key))
        return out


# ---------------------------------------------------------------------------
# RB / RD frame packing (relay links only)
# ---------------------------------------------------------------------------

_ITEM_HEAD = struct.Struct("<iQ2sI")   # origin, epoch, magic, len
_RD_HEAD = struct.Struct("<i2sI")      # target, magic, len


def child_epoch_value(relay_id: int, counter: int) -> int:
    """Wire epoch for a relay's Nth connection from a child: the
    assigning relay's id rides the high bits, so epochs are globally
    unique ACROSS relays — a leaf that re-homes from relay A to relay
    B (same top-level link from the root's view) can never collide
    with stale epoch-counter values still in flight from A."""
    return ((relay_id & 0x7FFFFFFF) << 32) | (counter & 0xFFFFFFFF)


def pack_rb_items(items) -> bytes:
    """items: [(origin_rank, epoch, magic, payload)].  The epoch is
    the direct parent's per-child connection counter composited with
    its relay id (child_epoch_value): the root discards stream items
    whose epoch does not match the rank's current attachment, so
    frames in flight from a superseded child socket — even one on a
    DIFFERENT relay after an intra-subtree re-home — can never be
    double-counted against the resume cursor."""
    parts = [struct.pack("<I", len(items))]
    for origin, epoch, magic, payload in items:
        parts.append(_ITEM_HEAD.pack(origin, epoch, magic,
                                     len(payload)))
        parts.append(payload)
    return b"".join(parts)


def unpack_rb_items(buf: bytes) -> List[Tuple[int, int, bytes, bytes]]:
    (count,) = struct.unpack_from("<I", buf, 0)
    off = 4
    items = []
    for _ in range(count):
        origin, epoch, magic, ln = _ITEM_HEAD.unpack_from(buf, off)
        off += _ITEM_HEAD.size
        items.append((origin, epoch, magic, buf[off:off + ln]))
        off += ln
    return items


def pack_rd(target: int, magic: bytes, payload: bytes) -> bytes:
    return _RD_HEAD.pack(target, magic, len(payload)) + payload


def unpack_rd(buf: bytes) -> Tuple[int, bytes, bytes]:
    target, magic, ln = _RD_HEAD.unpack_from(buf, 0)
    off = _RD_HEAD.size
    return target, magic, buf[off:off + ln]


# ---------------------------------------------------------------------------
# selector-based frame mux (the root's batched recv loop + relays)
# ---------------------------------------------------------------------------

_MAX_FRAME = 512 << 20   # frame-length sanity bound per link


class FrameMux:
    """One thread draining frames from many BLOCKING sockets via a
    selector: select() gates readability, each readiness event costs
    exactly one recv() (which cannot block on a readable socket), and
    per-link buffers re-assemble length-prefixed frames.  Replaces
    thread-per-link on the root/relays, where the link count is what
    the tree bounds.  Sends stay plain blocking sendall from caller
    threads, same as the thread-per-link model."""

    def __init__(self, on_frame, on_close, name="hvd-mux",
                 on_data=None):
        # on_frame(token, magic, payload) -> False to close the link;
        # on_close(token) fires exactly once per removed link;
        # on_data(token) fires on every received chunk (liveness
        # refresh for large frames trickling in slower than a frame).
        self._on_frame = on_frame
        self._on_close = on_close
        self._on_data = on_data
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._pending: deque = deque()   # ("add", token, sock) | ("close", token)
        self._links: Dict[object, Tuple[socket.socket, bytearray]] = {}
        self._lock = threading.Lock()
        self._stop_flag = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)

    def start(self):
        self._thread.start()

    def add(self, token, sock: socket.socket):
        with self._lock:
            self._pending.append(("add", token, sock))
        self._wake()

    def close_link(self, token):
        with self._lock:
            self._pending.append(("close", token, None))
        self._wake()

    def stop(self):
        self._stop_flag.set()
        self._wake()
        self._thread.join(timeout=5.0)

    def _wake(self):
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _drain_pending_locked(self):
        while self._pending:
            op, token, sock = self._pending.popleft()
            if op == "add":
                self._links[token] = (sock, bytearray())
                try:
                    # The socket may have been closed by a racing
                    # teardown before we got to register it.
                    # hvdlint: bounded-by(selector-registered link:
                    # recv only fires on EVENT_READ, select polls 0.2s)
                    sock.settimeout(None)
                    self._sel.register(sock, selectors.EVENT_READ,
                                       token)
                except (KeyError, ValueError, OSError):
                    self._links.pop(token, None)
                    self._on_close(token)
            else:
                self._drop(token)

    def _drop(self, token):
        ent = self._links.pop(token, None)
        if ent is None:
            return
        sock, _ = ent
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass
        self._on_close(token)

    def _run(self):
        while not self._stop_flag.is_set():
            with self._lock:
                self._drain_pending_locked()
            events = self._sel.select(timeout=0.2)
            for key, _ in events:
                if key.data is None:   # wakeup pipe
                    try:
                        # hvdlint: bounded-by(EVENT_READ-gated: data
                        # is already waiting when select returns)
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    continue
                token = key.data
                ent = self._links.get(token)
                if ent is None:
                    continue
                sock, buf = ent
                try:
                    # hvdlint: bounded-by(EVENT_READ-gated: data is
                    # already waiting when select returns)
                    chunk = sock.recv(262144)
                except OSError:
                    chunk = b""
                if not chunk:
                    self._drop(token)
                    continue
                if self._on_data is not None:
                    self._on_data(token)
                buf.extend(chunk)
                if not self._parse(token, buf):
                    self._drop(token)
        # teardown: close everything without firing callbacks twice
        with self._lock:
            self._drain_pending_locked()
        for token in list(self._links):
            self._drop(token)
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _parse(self, token, buf: bytearray) -> bool:
        while len(buf) >= 6:
            magic = bytes(buf[:2])
            (ln,) = struct.unpack_from("<I", buf, 2)
            if ln > _MAX_FRAME:
                logger.error("oversized frame (%d bytes) on %r; "
                             "dropping the link", ln, token)
                return False
            if len(buf) < 6 + ln:
                return True
            payload = bytes(buf[6:6 + ln])
            del buf[:6 + ln]
            try:
                keep = self._on_frame(token, magic, payload)
            except Exception:
                logger.exception("frame handler failed on %r", token)
                return False
            if keep is False:
                return False
        return True


# ---------------------------------------------------------------------------
# the relay server
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, magic: bytes, payload: bytes):
    """THE length-prefixed wire framing primitive (both hops of the
    tree and the flat star share it; controller_net aliases it)."""
    sock.sendall(magic + struct.pack("<I", len(payload)) + payload)


def recv_frame(sock: socket.socket):
    """Blocking counterpart of send_frame; None on EOF."""
    def recv_exact(n):
        b = b""
        while len(b) < n:
            # hvdlint: bounded-by(callers arm settimeout — accept
            # loops the registration timeout, recv loops the liveness
            # poll period; socket.timeout propagates to them)
            chunk = sock.recv(n - len(b))
            if not chunk:
                return None
            b += chunk
        return b
    head = recv_exact(6)
    if head is None:
        return None
    magic, ln = head[:2], struct.unpack("<I", head[2:])[0]
    payload = recv_exact(ln)
    if payload is None:
        return None
    return magic, payload


class _ChildToken:
    __slots__ = ("kind", "ident", "epoch", "sock", "clean")

    def __init__(self, kind, ident, epoch, sock):
        self.kind = kind      # "leaf" | "relay"
        self.ident = ident    # rank | relay id
        self.epoch = epoch
        self.sock = sock
        self.clean = False

    def __repr__(self):
        return "<%s %s e%d>" % (self.kind, self.ident, self.epoch)


class RelayServer:
    """A stateless interior node of the relay tree (module docstring).
    Fail-stop by design: any parent-link death or internal error shuts
    the relay down, closing every child socket so the children re-home
    through their ancestor chain — the relay holds no stream state
    worth saving."""

    def __init__(self, relay_id: int, parent_addrs: List[str],
                 bind_addr: str = "127.0.0.1", port: int = 0,
                 liveness_interval_s: float = 0.0,
                 liveness_timeout_s: float = 0.0,
                 registration_timeout_s: float = 30.0,
                 depth_below: int = 1):
        self.relay_id = relay_id
        self.depth_below = depth_below
        self._parent_addrs = list(parent_addrs)
        self.liveness_interval_s = liveness_interval_s
        self.liveness_timeout_s = liveness_timeout_s or \
            2.0 * liveness_interval_s
        self.registration_timeout_s = registration_timeout_s
        self._stop = threading.Event()
        self._wedged = False
        self._lock = threading.Lock()          # children/routes/queue
        self._send_lock = threading.Lock()     # parent uplink socket
        self._children: Dict[object, _ChildToken] = {}
        self._eligible: set = set()            # tokens past their WE ack
        self._route: Dict[int, _ChildToken] = {}   # leaf rank -> child
        self._child_epoch: Dict[int, int] = {}     # per-rank conn counter
        self._last_heard: Dict[object, float] = {}
        self._lheap = DeadlineHeap()
        self._up_q: deque = deque()   # ("item", (o,e,m,p)) | ("raw", m, p)
        self._up_ev = threading.Event()
        self._last_uplink_t = time.monotonic()
        self._mr_pending: Dict[object, Tuple[List[int], dict]] = {}
        # --- parent link (connect BEFORE accepting children, so a
        # child registration always has somewhere to go) ---
        self._parent = self._connect_parent()
        # --- child listener ---
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_addr, port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self._mux = FrameMux(self._on_child_frame, self._on_child_close,
                             name="hvd-relay%d-mux" % relay_id)
        self._mux.start()
        self._threads = []
        for target, name in (
                (self._accept_loop, "accept"),
                (self._parent_recv_loop, "parent"),
                (self._uplink_loop, "uplink")):
            t = threading.Thread(target=target, daemon=True,
                                 name="hvd-relay%d-%s" % (relay_id,
                                                          name))
            t.start()
            self._threads.append(t)
        if self.liveness_interval_s > 0:
            t = threading.Thread(target=self._liveness_loop,
                                 daemon=True,
                                 name="hvd-relay%d-liveness" % relay_id)
            t.start()
            self._threads.append(t)
        logger.info("relay %d up on port %d (depth_below=%d, parent "
                    "chain %s)", relay_id, self.port, depth_below,
                    self._parent_addrs)

    # ------------------------------------------------------------------
    # parent link
    # ------------------------------------------------------------------
    def _connect_parent(self) -> socket.socket:
        deadline = time.monotonic() + env_mod.start_timeout()
        last_err = None
        while time.monotonic() < deadline:
            for addr in self._parent_addrs:
                host, port = addr.rsplit(":", 1)
                try:
                    s = socket.create_connection((host, int(port)),
                                                 timeout=5.0)
                except OSError as e:
                    last_err = e
                    continue
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                reg = struct.pack("<i", relay_reg_rank(self.relay_id))
                reg += json.dumps({"relay": self.relay_id,
                                   "depth_below": self.depth_below
                                   }).encode()
                try:
                    send_frame(s, b"RQ", reg)
                except OSError as e:
                    last_err = e
                    s.close()
                    continue
                return s
            time.sleep(0.2)
        raise ConnectionError(
            "relay %d could not reach a parent in %s: %s"
            % (self.relay_id, self._parent_addrs, last_err))

    def _parent_recv_loop(self):
        sock = self._parent
        if self.liveness_interval_s > 0:
            sock.settimeout(max(self.liveness_timeout_s / 4.0, 0.05))
        try:
            while not self._stop.is_set():
                try:
                    frame = recv_frame(sock)
                except socket.timeout:
                    continue
                except OSError:
                    frame = None
                if frame is None:
                    break
                if self._wedged:
                    # SIGSTOP analog for drills: hold everything.
                    while self._wedged and not self._stop.is_set():
                        time.sleep(0.02)
                magic, payload = frame
                _RELAY_FRAMES.inc(1, dir="down")
                if _fr.ENABLED and magic == b"HB":
                    # Downlink HB arrival: one half of the HB round
                    # trip blackbox_merge aligns this relay's clock by.
                    _fr.record(_fr.HB_RX,
                               rank="relay%d" % self.relay_id,
                               role="relay")
                if magic == MAGIC_RELAY_DOWN:
                    self._route_down(payload)
                    continue
                if magic == b"MQ":
                    # Metrics poll generation boundary: whatever the
                    # previous poll accumulated goes up now, so a slow
                    # child can delay but never wedge aggregation.
                    self._flush_metrics_agg()
                self._broadcast_children(magic, payload)
        finally:
            # Fail-stop: parent gone (or shutdown) -> the subtree must
            # re-home; closing every child socket is the signal.
            self.shutdown()

    def _route_down(self, payload: bytes):
        target, magic, inner = unpack_rd(payload)
        with self._lock:
            token = self._route.get(target)
            if token is not None and token.kind == "leaf":
                # First RD for a child is always the root's WE ack: it
                # opens the broadcast gate (broadcasts the root sent
                # BEFORE it registered this rank were never logged in
                # its out-log, so delivering them would desync the
                # resume cursor).
                self._eligible.add(token)
        if token is None:
            logger.warning("relay %d: no route for targeted %s frame "
                           "to rank %d", self.relay_id,
                           magic.decode("ascii", "replace"), target)
            return
        try:
            if token.kind == "leaf":
                send_frame(token.sock, magic, inner)
            else:
                send_frame(token.sock, MAGIC_RELAY_DOWN, payload)
        except OSError:
            pass   # child death is handled by the mux EOF path

    def _broadcast_children(self, magic: bytes, payload: bytes):
        with self._lock:
            targets = [t for t in self._children.values()
                       if t.kind == "relay" or t in self._eligible]
        for token in targets:
            try:
                send_frame(token.sock, magic, payload)
            except OSError:
                pass

    # ------------------------------------------------------------------
    # children
    # ------------------------------------------------------------------
    def _accept_loop(self):
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.registration_timeout_s)
            try:
                frame = recv_frame(conn)
            except (socket.timeout, OSError):
                conn.close()
                continue
            if frame is None:
                conn.close()
                continue
            magic, payload = frame
            if magic != b"RQ" or len(payload) < 4:
                # Garbage first frame (port scanner, misdirected
                # peer, wrong kind): drop the connection, never the
                # accept loop — registration is always an RQ frame.
                conn.close()
                continue
            rank = struct.unpack("<i", payload[:4])[0]
            # hvdlint: bounded-by(registered child moves onto the
            # selector mux below; select polls at 0.2s)
            conn.settimeout(None)
            if is_relay_reg(rank):
                token = _ChildToken("relay", relay_id_from_reg(rank),
                                    0, conn)
                with self._lock:
                    self._children[token] = token
                    self._last_heard[token] = time.monotonic()
                    self._schedule_child_locked(token)
                self._mux.add(token, conn)
                continue
            with self._lock:
                counter = self._child_epoch.get(rank, 0) + 1
                self._child_epoch[rank] = counter
                epoch = child_epoch_value(self.relay_id, counter)
                token = _ChildToken("leaf", rank, epoch, conn)
                old = self._route.get(rank)
                self._children[token] = token
                self._route[rank] = token
                self._last_heard[token] = time.monotonic()
                self._schedule_child_locked(token)
            if _fr.ENABLED:
                # Child attach + epoch bump: a postmortem can prove
                # which connection epoch a frame in flight belonged to.
                _fr.record(_fr.RELAY_ATTACH,
                           rank="relay%d" % self.relay_id,
                           role="relay", peer=rank, cyc=epoch,
                           superseded=old is not None)
            if old is not None and old.kind == "leaf":
                # Supersede only a stale connection of the SAME leaf.
                # A relay-kind route token means the rank used to be
                # reachable through a (healthy) sub-relay — closing
                # that link would fail-stop its whole subtree; the
                # route replacement above is all that's needed.
                self._mux.close_link(old)
            # Forward the registration (fresh or resume) up; the root
            # answers with a targeted RD(WE) that opens this child's
            # broadcast gate.
            self._enqueue_item(rank, epoch, MAGIC_REGISTER, payload)
            self._mux.add(token, conn)

    def _schedule_child_locked(self, token):
        if self.liveness_interval_s > 0:
            self._lheap.schedule(token, time.monotonic() +
                                 self._child_deadline(token))

    def _child_deadline(self, token) -> float:
        if token.kind == "leaf":
            return self.liveness_timeout_s
        return env_mod.depth_aware_liveness_timeout(
            self.liveness_timeout_s, max(1, self.depth_below - 1))

    def _on_child_frame(self, token, magic: bytes, payload: bytes):
        if self._stop.is_set():
            return False
        self._last_heard[token] = time.monotonic()
        if self._wedged:
            while self._wedged and not self._stop.is_set():
                time.sleep(0.02)
        _RELAY_FRAMES.inc(1, dir="up")
        if token.kind == "relay":
            if magic == MAGIC_RELAY_BATCH:
                # Learn routes from the item origins, then forward the
                # original bytes verbatim (no re-pack).
                try:
                    items = unpack_rb_items(payload)
                except (struct.error, IndexError):
                    logger.error("relay %d: corrupt RB from %r",
                                 self.relay_id, token)
                    return False
                with self._lock:
                    for origin, _, _, _ in items:
                        self._route[origin] = token
                self._enqueue_raw(magic, payload)
                return True
            if magic == b"HB":
                if _fr.ENABLED:
                    _fr.record(_fr.HB_RX,
                               rank="relay%d" % self.relay_id,
                               role="relay", relay=token.ident)
                return True   # sub-relay liveness only
            if magic in (MAGIC_METRICS_AGG,):
                self._note_metrics(token, payload)
                return True
            if magic == MAGIC_RELAY_LOST:
                self._enqueue_raw(magic, payload)
                return True
            logger.warning("relay %d: unexpected %s frame from %r",
                           self.relay_id,
                           magic.decode("ascii", "replace"), token)
            return True
        # leaf child
        if magic == b"HB":
            if _fr.ENABLED:
                _fr.record(_fr.HB_RX, rank="relay%d" % self.relay_id,
                           role="relay", peer=token.ident)
            return True    # consumed: one relay HB stands in for all
        if magic == b"MR":
            self._note_metrics(token, payload)
            return True
        self._enqueue_item(token.ident, token.epoch, magic, payload)
        return True

    def _on_child_close(self, token):
        with self._lock:
            if self._children.pop(token, None) is None:
                return   # superseded/already handled
            self._eligible.discard(token)
            self._last_heard.pop(token, None)
            self._mr_pending.pop(token, None)
            lost = self._routed_ranks_locked(token)
            for r, _ in lost:
                if self._route.get(r) is token:
                    self._route.pop(r, None)
        if _fr.ENABLED and not self._stop.is_set() and \
                token.kind == "relay":
            # An interior sub-relay's link died: this parent is the
            # only witness that can NAME it — the root's RL notice
            # carries the reporter's id, not the dead hop's.
            _fr.record(_fr.RELAY_DOWN,
                       rank="relay%d" % self.relay_id, role="relay",
                       relay=token.ident,
                       reason="child relay link closed at relay %d"
                              % self.relay_id)
        if self._stop.is_set() or not lost:
            return
        self._report_lost(lost, "disconnect",
                          "child link closed at relay %d"
                          % self.relay_id)

    def _routed_ranks_locked(self, token) -> List[tuple]:
        """(rank, epoch) pairs this child link covers.  Direct leaf
        children carry their connection epoch (the root can prove the
        notice refers to the CURRENT attachment); ranks routed through
        a sub-relay carry None — the root then arms a suspicion clock
        instead of detaching (see controller_net._handle_relay_lost)."""
        if token.kind == "leaf":
            return [(token.ident, token.epoch)]
        return [(r, None) for r, t in self._route.items()
                if t is token]

    def _report_lost(self, ranks: List[tuple], kind: str, reason: str):
        _CHILD_LOST.inc(len(ranks), kind=kind)
        if _fr.ENABLED:
            _fr.record(_fr.RELAY_LOST,
                       rank="relay%d" % self.relay_id, role="relay",
                       lost_kind=kind, reason=reason,
                       ranks=[r for r, _ in ranks])
        self._enqueue_raw(MAGIC_RELAY_LOST, json.dumps(
            {"ranks": ranks, "kind": kind, "reason": reason}).encode())

    # ------------------------------------------------------------------
    # uplink batching
    # ------------------------------------------------------------------
    def _enqueue_item(self, origin, epoch, magic, payload):
        with self._lock:
            self._up_q.append(("item", (origin, epoch, magic, payload)))
        self._up_ev.set()

    def _enqueue_raw(self, magic, payload):
        with self._lock:
            self._up_q.append(("raw", magic, payload))
        self._up_ev.set()

    def _uplink_loop(self):
        """Drain-all-pending batching (the PR 4 coalescing precedent):
        whatever accumulated while the previous send was on the wire
        goes up as ONE RB frame — batching under load, zero added
        latency when idle."""
        while not self._stop.is_set():
            if not self._up_ev.wait(timeout=0.5):
                continue
            self._up_ev.clear()
            while True:
                with self._lock:
                    if not self._up_q:
                        break
                    batch: List[tuple] = []
                    raw = None
                    while self._up_q:
                        entry = self._up_q[0]
                        if entry[0] == "item":
                            self._up_q.popleft()
                            batch.append(entry[1])
                        else:
                            if batch:
                                break
                            raw = self._up_q.popleft()
                            break
                if self._wedged:
                    while self._wedged and not self._stop.is_set():
                        time.sleep(0.02)
                try:
                    with self._send_lock:
                        self._last_uplink_t = time.monotonic()
                        if batch:
                            _UPLINK_ITEMS.observe(len(batch))
                            send_frame(self._parent, MAGIC_RELAY_BATCH,
                                        pack_rb_items(batch))
                        elif raw is not None:
                            send_frame(self._parent, raw[1], raw[2])
                except OSError:
                    self.shutdown()
                    return

    # ------------------------------------------------------------------
    # liveness + heartbeats
    # ------------------------------------------------------------------
    def _liveness_loop(self):
        period = max(self.liveness_interval_s / 2.0, 0.05)
        while not self._stop.wait(period):
            if self._wedged:
                continue
            now = time.monotonic()
            # Relay HB up (suppressed while real uplink flows).
            if now - self._last_uplink_t >= self.liveness_interval_s:
                try:
                    with self._send_lock:
                        self._last_uplink_t = now
                        send_frame(self._parent, b"HB", b"")
                    if _fr.ENABLED:
                        # Uplink HB departure: the other half of the
                        # clock-alignment round trip.
                        _fr.record(_fr.FRAME_TX,
                                   rank="relay%d" % self.relay_id,
                                   role="relay", frame="HB", nbytes=6)
                except OSError:
                    self.shutdown()
                    return
            with self._lock:
                due = self._lheap.due(now, self._deadline_for_locked)
                silent = [(t, self._routed_ranks_locked(t))
                          for t in due]
            for token, ranks in silent:
                logger.warning(
                    "relay %d: child %r silent past %.1fs; reporting "
                    "lost", self.relay_id, token,
                    self._child_deadline(token))
                if _fr.ENABLED and token.kind == "relay":
                    # A WEDGED sub-relay never says its own last word;
                    # the per-hop deadline here is the only evidence
                    # that names it.
                    _fr.record(_fr.RELAY_DOWN,
                               rank="relay%d" % self.relay_id,
                               role="relay", relay=token.ident,
                               reason="silent past the per-hop "
                                      "deadline at relay %d"
                                      % self.relay_id)
                if ranks:
                    self._report_lost(
                        ranks, "silent",
                        "silent past the per-hop deadline at relay %d"
                        % self.relay_id)
                self._mux.close_link(token)

    def _deadline_for_locked(self, token):
        if token not in self._children:
            return None
        heard = self._last_heard.get(token)
        if heard is None:
            return None
        return heard + self._child_deadline(token)

    # ------------------------------------------------------------------
    # metrics aggregation (MR -> MA)
    # ------------------------------------------------------------------
    def _note_metrics(self, token, payload: bytes):
        try:
            snap = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if token.kind == "leaf":
            entry = ([token.ident], snap)
        else:
            entry = (list(snap.get("ranks", [])),
                     snap.get("snapshot") or {})
        with self._lock:
            self._mr_pending[token] = entry
            live = set(self._children.values())
            complete = live and live.issubset(set(self._mr_pending))
        if complete:
            self._flush_metrics_agg()

    def _flush_metrics_agg(self):
        with self._lock:
            if not self._mr_pending:
                return
            pending, self._mr_pending = self._mr_pending, {}
        ranks: List[int] = []
        snaps = []
        for rlist, snap in pending.values():
            ranks.extend(rlist)
            snaps.append(snap)
        merged = metrics.merge_snapshots(snaps)
        _AGG_SNAPSHOTS.inc()
        self._enqueue_raw(MAGIC_METRICS_AGG, json.dumps(
            {"ranks": sorted(ranks), "snapshot": merged}).encode())

    # ------------------------------------------------------------------
    # lifecycle + drill hooks
    # ------------------------------------------------------------------
    def shutdown(self):
        if self._stop.is_set():
            return
        self._stop.set()
        if _fr.ENABLED:
            # Fail-stop: the relay's own last word in a postmortem.
            _fr.record(_fr.RELAY_DOWN,
                       rank="relay%d" % self.relay_id, role="relay",
                       relay=self.relay_id,
                       reason="fail-stop shutdown")
        self._up_ev.set()
        for s in (self._srv, self._parent):
            try:
                s.close()
            except OSError:
                pass
        with self._lock:
            children = list(self._children.values())
        for token in children:
            try:
                token.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                token.sock.close()
            except OSError:
                pass
        self._mux.stop()

    # Drill hooks (tools/chaos_soak.py): deterministic in-process
    # analogs of a relay process death / SIGSTOP / uplink cable pull.
    def debug_kill(self):
        """Abrupt relay death: every socket dies at once, exactly what
        a SIGKILL'd relay process looks like to its peers."""
        self.shutdown()

    def debug_wedge(self, on: bool = True):
        """SIGSTOP analog: stop forwarding in both directions and stop
        heartbeating, keep every socket open — only liveness deadlines
        can expose it."""
        self._wedged = on

    def debug_sever_parent(self):
        """Pull the uplink cable: the relay notices the dead parent
        link and fail-stops, severing its children (who re-home)."""
        try:
            self._parent.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._parent.close()
        except OSError:
            pass
