"""Runtime lock-order witness: deadlock hazards caught without the
deadlock.

The runtime half of the hvdlint suite (docs/static_analysis.md): the
static analyzers can prove a wait is bounded, but lock *ordering* is a
dynamic property — an ABBA inversion only exists on the interleaving
the scheduler happened to produce.  The witness makes every
interleaving count: while enabled, ``threading.Lock``/``RLock``
objects created by ``horovod_tpu`` code are wrapped, every
cross-lock acquisition edge (thread holds A, acquires B) is recorded
into a process-wide directed graph, and a cycle — the classic
watchdog/witness criterion from FreeBSD's ``witness(4)`` and the
TSAN lock-order-inversion detector the reference core relies on —
is reported *the first time both orders have ever been observed*,
whether or not the schedule actually deadlocked.

What a finding names (the postmortem contract of PR 9): both lock
creation sites (file:line), the acquisition stacks that witnessed
each edge of the cycle, and the threads involved.

Design constraints (the repo's standing instrumentation contract):

  * **one attribute check when disabled** — a wrapped lock's acquire
    is ``inner.acquire(...)`` plus ``if ENABLED:``; the perf pin in
    tests/test_lockwitness.py asserts it, exactly like failpoints and
    the flight recorder.  With the witness never enabled, *nothing*
    is wrapped and the cost is zero.
  * **opt-in** — ``HOROVOD_LOCKWITNESS=1`` arms it at ``hvd.init``;
    the ``lock_witness`` pytest fixture (tests/conftest.py) arms it
    around the chaos smoke and replay e2e suites and fails the test
    on any cycle.
  * **no wire or disk footprint** — pure in-memory graph, bounded by
    the number of locks created while armed (each wrapper is pinned
    so id()-keyed graph nodes can never alias a recycled address)
    plus the distinct lock pairs; ``reset()`` drops it all.

Scope and honesty notes:

  * Only locks *created while enabled* by code whose immediate caller
    lives under the configured package filter are wrapped (module-
    level locks created at import ride outside the window; the
    control-plane objects tests construct inside the window are the
    point).
  * ``threading.Condition()``'s internal ``RLock()`` is created from
    ``threading.py`` and is deliberately NOT wrapped (Conditions use
    private lock internals a wrapper must not break).
  * A cycle is reported when its edges were witnessed from at least
    ``MIN_THREADS`` (2) distinct threads — a single thread taking
    A→B then B→A after releasing cannot deadlock itself, but the
    same two orders split across threads can.
"""

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV_ENABLE = "HOROVOD_LOCKWITNESS"

# Frames belonging to the witness itself and to threading internals,
# skipped when attributing lock creations/acquisitions to caller
# code.  Exact paths, not suffixes — a user file named
# test_lockwitness.py must NOT be skipped.
_SELF_FILE = os.path.abspath(__file__).rstrip("co")  # .pyc -> .py
_THREADING_FILE = os.path.abspath(
    threading.__file__).rstrip("co")


def _is_internal_frame(filename: str) -> bool:
    f = os.path.abspath(filename).rstrip("co")
    return f == _SELF_FILE or f == _THREADING_FILE

# THE disabled-path gate: every wrapped acquire/release checks this
# one module attribute before any graph work.  enable()/disable() are
# the only writers.
ENABLED = False

# Cycle policy: edges of a reported cycle must come from at least
# this many distinct threads (see module docstring).
MIN_THREADS = 2

_STACK_LIMIT = 12          # frames kept per witnessing stack

_state_lock = threading.Lock()
# The REAL factories, captured at import and never cleared: a factory
# reference captured while patched must keep working after disable().
_orig_lock = threading.Lock
_orig_rlock = threading.RLock
_patched = False           # are threading.Lock/RLock our factories?
_package_filter = "horovod_tpu"

# lock ident (int) -> creation site "file:line"
_sites: Dict[int, str] = {}
# ident -> the wrapper itself (strong refs: id() keys must never be
# reused by the allocator while the graph holds edges naming them)
_live: Dict[int, object] = {}
# (a_ident, b_ident) -> mutable edge record {a_site, b_site,
# threads: set of witnessing thread names, stack: first witness}
_edges: Dict[Tuple[int, int], dict] = {}
# adjacency for cycle search: a_ident -> [b_ident, ...]
_succ: Dict[int, List[int]] = {}
# recorded findings: list of dicts (see _report_cycle)
_violations: List[dict] = []

# Armed-window generation: bumped by every enable().  Thread-local
# held/depth state is stamped with the generation it was written in
# and discarded when a new window starts — a thread that released a
# witnessed lock while DISABLED (release bookkeeping is skipped to
# keep the one-attribute-check contract) would otherwise carry stale
# held entries into the next armed window and fabricate edges there.
_gen = 0

_tls = threading.local()   # .held, .depth, .gen


def _held() -> List[int]:
    if getattr(_tls, "gen", None) != _gen:
        _tls.held, _tls.depth, _tls.gen = [], {}, _gen
    return _tls.held


def _depths() -> Dict[int, int]:
    if getattr(_tls, "gen", None) != _gen:
        _tls.held, _tls.depth, _tls.gen = [], {}, _gen
    return _tls.depth


def _creation_site() -> str:
    """file:line of the nearest stack frame outside this module and
    outside threading.py — the code that asked for the lock."""
    for frame, lineno in traceback.walk_stack(None):
        fn = frame.f_code.co_filename
        if _is_internal_frame(fn):
            continue
        return "%s:%d" % (fn, lineno)
    return "<unknown>"


def _witness_stack() -> str:
    out = []
    for frame, lineno in traceback.walk_stack(None):
        fn = frame.f_code.co_filename
        if _is_internal_frame(fn):
            continue
        out.append("%s:%d %s" % (fn, lineno, frame.f_code.co_name))
        if len(out) >= _STACK_LIMIT:
            break
    return " <- ".join(out)


def _find_path(start: int, goal: int) -> Optional[List[int]]:
    """DFS in the edge graph (caller holds _state_lock)."""
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == goal:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in _succ.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _report_cycle(path: List[int], closing_edge_stack: str) -> None:
    """``path`` is B..A for a new edge A->B that closed a cycle
    (caller holds _state_lock)."""
    edge_reports = []
    threads = set()
    nodes = path + [path[0]]
    for a, b in zip(nodes, nodes[1:]):
        ent = _edges[(a, b)]
        threads.update(ent["threads"])
        edge_reports.append({
            "from_site": ent["a_site"], "to_site": ent["b_site"],
            "thread": "/".join(sorted(ent["threads"])),
            "stack": ent["stack"],
        })
    if len(threads) < MIN_THREADS:
        return
    key = tuple(sorted(_sites.get(n, "?") for n in path))
    for v in _violations:
        if v["key"] == key:
            return   # already reported this site cycle
    _violations.append({
        "key": key,
        "sites": [_sites.get(n, "?") for n in path],
        "edges": edge_reports,
        "closing_stack": closing_edge_stack,
    })


def _note_acquired(ident: int) -> None:
    depths = _depths()
    if depths.get(ident, 0) > 0:
        depths[ident] += 1      # reentrant re-acquire: no new edge
        return
    depths[ident] = 1
    held = _held()
    if held:
        holder = held[-1]
        if holder != ident:
            edge = (holder, ident)
            tname = threading.current_thread().name
            # Warm-path fast exit: a repeat acquisition in the same
            # order BY A THREAD ALREADY ON THE EDGE pays two dict
            # probes, not a 12-frame stack walk.  A new thread on a
            # known edge re-runs the cycle check — a cycle first
            # suppressed by MIN_THREADS (single-thread inversion)
            # must surface the moment a second thread proves it
            # cross-thread (benign race: one redundant capture).
            ent = _edges.get(edge)
            if ent is None:
                stack = _witness_stack()
                with _state_lock:
                    ent = _edges.get(edge)
                    if ent is None:
                        _edges[edge] = {
                            "a_site": _sites.get(holder, "?"),
                            "b_site": _sites.get(ident, "?"),
                            "threads": {tname}, "stack": stack,
                        }
                        _succ.setdefault(holder, []).append(ident)
                        # Did ident -> ... -> holder already exist?
                        # Then this new edge closes a cycle.
                        path = _find_path(ident, holder)
                        if path is not None:
                            _report_cycle(path, stack)
                    else:
                        ent["threads"].add(tname)
            elif tname not in ent["threads"]:
                stack = _witness_stack()
                with _state_lock:
                    ent["threads"].add(tname)
                    path = _find_path(ident, holder)
                    if path is not None:
                        _report_cycle(path, stack)
    held.append(ident)


def _note_released(ident: int, all_depths: bool = False) -> None:
    depths = _depths()
    n = depths.get(ident, 0)
    if n > 1 and not all_depths:
        depths[ident] = n - 1
        return
    depths.pop(ident, None)
    held = _held()
    # Out-of-order release is legal (lock A released while B is
    # held): remove by value, not by stack pop.
    for i in range(len(held) - 1, -1, -1):
        if held[i] == ident:
            del held[i]
            break


class _WitnessLock:
    """Wrapper around a real lock: acquire/release bracketed by graph
    bookkeeping behind the ENABLED gate."""

    __slots__ = ("_inner", "_ident", "site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._ident = id(self)
        self.site = site
        with _state_lock:
            # The registry entry doubles as a STRONG reference: graph
            # nodes are keyed by id(), so a GC'd wrapper whose address
            # CPython reuses for a new lock would alias stale edges
            # and fabricate phantom cycles.  reset() drops them.
            _sites[self._ident] = site
            _live[self._ident] = self

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok and ENABLED:
            _note_acquired(self._ident)
        return ok

    def release(self):
        if ENABLED:
            _note_released(self._ident)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<WitnessLock %s of %r>" % (self.site, self._inner)


class _WitnessRLock(_WitnessLock):
    """RLock variant: per-thread depth counting in _note_acquired
    keeps reentrant re-acquires from self-edging the graph.

    It also forwards the private protocol ``threading.Condition``
    drives (``_is_owned`` / ``_release_save`` / ``_acquire_restore``)
    — without these, a witnessed RLock handed to ``Condition(...)``
    (e.g. ``ElasticDriver``'s assignment condition) would fall back
    to Condition's non-reentrant shims: ``acquire(False)`` succeeds
    reentrantly so the fallback ``_is_owned`` mis-reports not-owned
    and ``wait()`` raises on a correctly-held lock."""

    def locked(self):  # RLocks have no .locked() before 3.12
        fn = getattr(self._inner, "locked", None)
        return fn() if fn is not None else None

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        # Condition.wait: ALL recursion levels release at once.  The
        # witness depth rides along in the opaque state so a
        # reentrantly-held lock (depth >= 2) is restored at its TRUE
        # depth — otherwise the inner `with` block's release() after
        # wait() would drop the lock from the held list one release
        # early and hazard edges in that window would be lost.
        wdepth = 0
        if ENABLED:
            wdepth = _depths().get(self._ident, 0)
            _note_released(self._ident, all_depths=True)
        return (self._inner._release_save(), wdepth)

    def _acquire_restore(self, state):
        inner_state, wdepth = state
        self._inner._acquire_restore(inner_state)
        if ENABLED:
            _note_acquired(self._ident)
            if wdepth > 1:
                _depths()[self._ident] = wdepth


def _caller_wants_witness() -> bool:
    """True when the frame that called threading.Lock()/RLock() lives
    under the package filter (skipping threading.py itself, so
    Condition/Event internals stay unwrapped)."""
    for frame, _ in traceback.walk_stack(None):
        fn = os.path.abspath(frame.f_code.co_filename).rstrip("co")
        if fn == _SELF_FILE:
            continue
        if fn == _THREADING_FILE:
            # Immediate creator is threading internals (Condition /
            # Event building their own RLock): never wrap those.
            return False
        return _package_filter in frame.f_code.co_filename
    return False


def _lock_factory():
    if ENABLED and _caller_wants_witness():
        return _WitnessLock(_orig_lock(), _creation_site())
    return _orig_lock()


def _rlock_factory():
    if ENABLED and _caller_wants_witness():
        return _WitnessRLock(_orig_rlock(), _creation_site())
    return _orig_rlock()


def enable(package_filter: str = "horovod_tpu") -> None:
    """Patch threading.Lock/RLock so locks created by ``horovod_tpu``
    code (while enabled) are witnessed.  Idempotent."""
    global ENABLED, _patched, _package_filter, _gen
    with _state_lock:
        _package_filter = package_filter
        # New armed window: invalidate every thread's held/depth TLS
        # (see _gen above — releases skipped while disabled must not
        # leak held state into this window).
        _gen += 1
        if not _patched:
            threading.Lock = _lock_factory
            threading.RLock = _rlock_factory
            _patched = True
    ENABLED = True


def disable() -> None:
    """Restore threading.Lock/RLock and stop recording.  Existing
    wrapped locks keep working (their acquire degrades to the one
    attribute check), and a factory reference captured while armed
    (``from threading import Lock`` in a lazily-imported module)
    keeps producing raw locks — the originals stay bound forever."""
    global ENABLED, _patched
    ENABLED = False
    with _state_lock:
        if _patched:
            threading.Lock = _orig_lock
            threading.RLock = _orig_rlock
            _patched = False


def reset() -> None:
    """Drop the recorded graph and findings (fixture teardown)."""
    with _state_lock:
        _sites.clear()
        _live.clear()
        _edges.clear()
        _succ.clear()
        del _violations[:]


def cycles() -> List[dict]:
    """The recorded lock-order cycles (each: sites, edges with
    witnessing thread + stack, closing stack)."""
    with _state_lock:
        return list(_violations)


def edge_count() -> int:
    with _state_lock:
        return len(_edges)


def render_cycle(v: dict) -> str:
    lines = ["lock-order cycle between %d lock(s):" % len(v["sites"])]
    for site in v["sites"]:
        lines.append("  lock created at %s" % site)
    for e in v["edges"]:
        lines.append("  edge %s -> %s  [thread %s]" %
                     (e["from_site"], e["to_site"], e["thread"]))
        lines.append("    witnessed: %s" % e["stack"])
    return "\n".join(lines)


def assert_no_cycles() -> None:
    """Raise AssertionError naming every recorded cycle (the fixture
    and chaos-smoke gate)."""
    found = cycles()
    if found:
        raise AssertionError(
            "lock-order witness found %d cycle(s):\n%s" % (
                len(found),
                "\n\n".join(render_cycle(v) for v in found)))


def maybe_enable_from_env() -> bool:
    """Arm from HOROVOD_LOCKWITNESS (called by hvd.init)."""
    from . import env as _env
    if _env.env_bool(ENV_ENABLE):
        enable()
        return True
    return False
