"""JAX version-compat shims for the compiled data plane.

The collective backends target the modern ``jax.shard_map`` entry
point (with its ``check_vma`` kwarg); older installs only ship
``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
``check_rep``.  One shim keeps every compiled-collective call site
identical across versions instead of scattering try/except per site.
"""


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` on new JAX, ``jax.experimental.shard_map`` on
    old.  ``check_vma=None`` keeps the running version's own default;
    an explicit bool maps onto whichever replication-check kwarg the
    version spells (vma/rep)."""
    import jax

    kwargs = {}
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
