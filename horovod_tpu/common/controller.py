"""Coordinator/worker negotiation: matching requests into responses.

Mirrors the reference controller protocol (reference: controller.{h,cc}:
ComputeResponseList :69-449 — rank 0 collects Requests from all ranks,
counts readiness (IncrementTensorCount :942-965), validates shape/dtype/
op agreement (ConstructResponse :471-748, mismatch → Response::ERROR),
fuses (FuseResponses :777-914) and broadcasts the ordered ResponseList;
protocol spec in controller.h:69-102).

Two implementations:
  * LoopbackController — single process; every request matches instantly.
  * The multi-process controller lives in controller_net.py and reuses
    construct_response/IncrementTensorCount from here over a TCP store.
"""

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .fusion import fuse_responses
from .message import (DataType, Request, RequestType, Response,
                      ResponseType)

logger = logging.getLogger("horovod_tpu.controller")

_REQ_TO_RESP = {
    RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
    RequestType.ALLGATHER: ResponseType.ALLGATHER,
    RequestType.BROADCAST: ResponseType.BROADCAST,
    RequestType.JOIN: ResponseType.JOIN,
    RequestType.ADASUM: ResponseType.ADASUM,
    RequestType.ALLTOALL: ResponseType.ALLTOALL,
    RequestType.REDUCESCATTER: ResponseType.REDUCESCATTER,
    RequestType.BARRIER: ResponseType.BARRIER,
}


def construct_response(name: str, msgs: List[Request], size: int,
                       joined_ranks: Set[int]) -> Response:
    """Validate the per-rank requests for one tensor and build a Response.

    Mismatched type/op/root/shape across ranks yields an ERROR response
    whose message names the offending ranks, matching reference
    ConstructResponse semantics (controller.cc:471-748).
    """
    assert msgs
    first = msgs[0]
    err = None

    for m in msgs[1:]:
        if m.request_type != first.request_type:
            err = (f"Mismatched collective operations: rank "
                   f"{first.request_rank} requested "
                   f"{first.request_type.name}, rank {m.request_rank} "
                   f"requested {m.request_type.name}.")
            break
        if m.tensor_type != first.tensor_type:
            err = (f"Mismatched data types for tensor {name}: rank "
                   f"{first.request_rank} has "
                   f"{DataType(first.tensor_type).name}, rank "
                   f"{m.request_rank} has {DataType(m.tensor_type).name}.")
            break
        if m.reduce_op != first.reduce_op:
            err = (f"Mismatched reduction ops for tensor {name}.")
            break
        if (m.prescale_factor != first.prescale_factor or
                m.postscale_factor != first.postscale_factor):
            err = f"Mismatched prescale/postscale factors for tensor {name}."
            break
        if first.request_type == RequestType.BROADCAST and \
                m.root_rank != first.root_rank:
            err = (f"Mismatched broadcast root ranks for tensor {name}: "
                   f"{first.root_rank} vs {m.root_rank}.")
            break
        if first.request_type in (RequestType.ALLREDUCE,
                                  RequestType.ADASUM,
                                  RequestType.BROADCAST) and \
                m.tensor_shape != first.tensor_shape:
            err = (f"Mismatched shapes for tensor {name}: rank "
                   f"{first.request_rank} has {first.tensor_shape}, rank "
                   f"{m.request_rank} has {m.tensor_shape}.")
            break
        if first.request_type in (RequestType.ALLGATHER,
                                  RequestType.ALLTOALL,
                                  RequestType.REDUCESCATTER) and \
                m.tensor_shape[1:] != first.tensor_shape[1:]:
            err = (f"Mismatched non-first dimensions for tensor {name}.")
            break

    if err is None and first.request_type == RequestType.ALLTOALL:
        group = len(first.process_set_ranks) or size
        for m in msgs:
            # 0-d tensors are promoted to one row by the data plane
            # (same convention as the allgather sizes above).
            dim0 = m.tensor_shape[0] if m.tensor_shape else 1
            if len(m.splits) != group:
                err = (f"Alltoall splits for tensor {name}: rank "
                       f"{m.request_rank} sent {len(m.splits)} entries "
                       f"for a group of {group}.")
                break
            if any(s < 0 for s in m.splits):
                err = (f"Alltoall splits for tensor {name}: rank "
                       f"{m.request_rank} sent negative splits "
                       f"{list(m.splits)}.")
                break
            if sum(m.splits) != dim0:
                # A ragged lookup batch is the common way to get here:
                # name the rank and both sums so the off-by-N is
                # visible without a debugger.
                err = (f"Alltoall splits for tensor {name}: rank "
                       f"{m.request_rank} splits {list(m.splits)} sum "
                       f"to {sum(m.splits)} but must sum to the first "
                       f"dimension ({dim0}); its tensor sends {dim0} "
                       f"rows, its splits account for "
                       f"{sum(m.splits)}.")
                break

    if err is not None:
        return Response(response_type=ResponseType.ERROR,
                        tensor_names=[name], error_message=err,
                        process_set_id=first.process_set_id)

    resp = Response(
        response_type=_REQ_TO_RESP[first.request_type],
        tensor_names=[name],
        tensor_type=first.tensor_type,
        prescale_factor=first.prescale_factor,
        postscale_factor=first.postscale_factor,
        process_set_id=first.process_set_id,
        root_rank=first.root_rank,
        reduce_op=first.reduce_op,
        tensor_shapes=[tuple(first.tensor_shape)],
        process_set_ranks=tuple(first.process_set_ranks),
    )
    if first.request_type == RequestType.ALLGATHER:
        # Record each participating rank's first-dimension size in
        # GROUP order (process-set ranks when given, else world rank
        # order) — consumers slice tensor_sizes in group_size strides
        # (xla_ops/ring_ops allgather, fusion, split_response); joined
        # (departed) ranks contribute zero rows.
        by_rank = {m.request_rank: m for m in msgs}
        ranks = list(first.process_set_ranks) or list(range(size))
        sizes = []
        for r in ranks:
            if r in by_rank:
                shape = by_rank[r].tensor_shape
                sizes.append(shape[0] if shape else 1)
            else:
                sizes.append(0)
        resp.tensor_sizes = sizes
    elif first.request_type == RequestType.ALLTOALL:
        # Flattened group×group send-split matrix, rows in GROUP order
        # (row g = group-rank g's send splits): rank g's recv splits
        # are column g.  Piggybacked on negotiation so the data plane
        # never needs its own split-exchange collective (reference:
        # AlltoallGetRecvSplits, mpi_controller.cc:212-223).  Joined
        # (departed) ranks contribute zero rows.
        by_rank = {m.request_rank: m for m in msgs}
        ranks = list(first.process_set_ranks) or list(range(size))
        group = len(ranks)
        matrix = []
        for r in ranks:
            if r in by_rank:
                matrix.extend(int(s) for s in by_rank[r].splits)
            else:
                matrix.extend([0] * group)
        resp.tensor_sizes = matrix
    return resp


@dataclass
class MessageTable:
    """Pending per-tensor request accumulation on the coordinator
    (IncrementTensorCount, controller.cc:942-965).  Keyed by
    (process_set_id, tensor_name): the SAME tensor name may be in
    flight on different process sets concurrently — the reference
    allows this structurally by giving every process set its own
    controller (process_set.h ProcessSetTable); a name-only key mixes
    the negotiations and wedges both sets."""
    entries: Dict[tuple, List[Request]] = field(default_factory=dict)

    @staticmethod
    def key(req: Request) -> tuple:
        return (req.process_set_id, req.tensor_name)

    def increment(self, req: Request, required: int,
                  joined_count: int = 0) -> bool:
        msgs = self.entries.setdefault(self.key(req), [])
        msgs.append(req)
        return len(msgs) + joined_count >= required

    def pop(self, key: tuple) -> List[Request]:
        return self.entries.pop(key, [])

    def ready_count(self, key: tuple) -> int:
        return len(self.entries.get(key, []))


class Controller:
    """Base interface; subclasses implement the cross-rank exchange."""

    def __init__(self, state):
        self.state = state
        self.size = state.rank_info.size
        self.rank = state.rank_info.rank
        self.joined_ranks: Set[int] = set()
        self.last_joined_rank = -1

    def is_coordinator(self) -> bool:
        return self.rank == 0

    def compute_response_list(self, pending: List[Request], entry_sizes,
                              threshold_bytes: int
                              ) -> Tuple[List[Response], List[Request]]:
        raise NotImplementedError

    def synchronize_parameters(self, params: dict) -> dict:
        """Broadcast autotuner-chosen knobs from rank 0 (reference:
        Controller::SynchronizeParameters, controller.cc:39-53)."""
        return params


class LoopbackController(Controller):
    """Single-process controller: all requests are instantly matched.

    This is also the negotiation model used when one process drives a
    whole TPU slice: there is exactly one program, so ordering is already
    deterministic and negotiation degenerates to validation + fusion.
    """

    def compute_response_list(self, pending, entry_sizes, threshold_bytes):
        responses: List[Response] = []
        group_ids = {}
        for req in pending:
            group_ids[MessageTable.key(req)] = req.group_id
            if req.request_type == RequestType.JOIN:
                self.joined_ranks.add(req.request_rank)
                self.last_joined_rank = req.request_rank
                responses.append(Response(
                    response_type=ResponseType.JOIN,
                    tensor_names=[req.tensor_name],
                    last_joined_rank=req.request_rank,
                    process_set_id=req.process_set_id))
                continue
            responses.append(construct_response(
                req.tensor_name, [req], 1, self.joined_ranks))
        fused = fuse_responses(responses, entry_sizes, threshold_bytes,
                               group_ids)
        return fused, []
