"""Wire protocol: Request / Response and compact binary serialization.

Mirrors the reference's coordinator message schema (reference:
common/message.h:— Request{rank,type,dtype,name,root,device,shape,
pre/postscale} and Response{type,names[],dtype,error,devices[],sizes[]},
serialized with FlatBuffers via wire/message.fbs).  Here the codec is a
hand-rolled little-endian struct framing that the C++ core can read
without a schema compiler.
"""

import enum
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


class DataType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10


_NP_TO_DT = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.bool_): DataType.BOOL,
}

_DT_TO_NP = {v: k for k, v in _NP_TO_DT.items()}

_DT_SIZE = {
    DataType.UINT8: 1, DataType.INT8: 1, DataType.UINT16: 2,
    DataType.INT16: 2, DataType.INT32: 4, DataType.INT64: 8,
    DataType.FLOAT16: 2, DataType.FLOAT32: 4, DataType.FLOAT64: 8,
    DataType.BOOL: 1, DataType.BFLOAT16: 2,
}


def dtype_of(array) -> DataType:
    """Map a numpy/jax array dtype to the wire DataType."""
    name = str(array.dtype)
    if name == "bfloat16":
        return DataType.BFLOAT16
    return _NP_TO_DT[np.dtype(name)]


def dtype_size(dt: DataType) -> int:
    return _DT_SIZE[dt]


def np_dtype(dt: DataType):
    if dt == DataType.BFLOAT16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return _DT_TO_NP[dt]


class RequestType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    REDUCESCATTER = 6
    BARRIER = 7


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    REDUCESCATTER = 6
    BARRIER = 7
    ERROR = 8


@dataclass
class Request:
    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_shape: Tuple[int, ...] = ()
    tensor_type: DataType = DataType.FLOAT32
    root_rank: int = -1
    device: int = 0
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    process_set_id: int = 0
    # Horovod reduce op requested ("Sum"/"Average"/"Adasum"/...)
    reduce_op: str = "Sum"
    # Member ranks of the process set (empty = global world).  Carried on
    # the wire so the coordinator knows the required count without a
    # separate registration protocol.
    process_set_ranks: Tuple[int, ...] = ()
    # Grouped-submission id (-1 = ungrouped).  Members of one group are
    # kept atomic by the fusion planner even past the fusion threshold
    # (reference: group_table.{h,cc}, controller.cc:199-223).
    group_id: int = -1
    # Alltoall send splits (dim-0 rows per destination, group order).
    # Carried on the wire so the coordinator can assemble every rank's
    # recv splits into the Response — saving the data plane a full
    # allgather round per uneven alltoall (reference:
    # AlltoallGetRecvSplits, mpi_controller.cc:212-223, which
    # piggybacks the split exchange on negotiation the same way).
    splits: Tuple[int, ...] = ()

    def nbytes(self) -> int:
        n = 1
        for d in self.tensor_shape:
            n *= d
        return n * dtype_size(self.tensor_type)

    def to_bytes(self) -> bytes:
        name_b = self.tensor_name.encode()
        op_b = self.reduce_op.encode()
        shape = self.tensor_shape
        psr = self.process_set_ranks
        spl = self.splits
        head = struct.pack(
            "<iiiiiddiiiHHHH", self.request_rank, int(self.request_type),
            int(self.tensor_type), self.root_rank, self.device,
            self.prescale_factor, self.postscale_factor,
            self.process_set_id, self.group_id, len(shape), len(name_b),
            len(op_b), len(psr), len(spl))
        return (head + struct.pack(f"<{len(shape)}q", *shape) + name_b +
                op_b + struct.pack(f"<{len(psr)}i", *psr) +
                struct.pack(f"<{len(spl)}q", *spl))

    @classmethod
    def from_bytes(cls, data: bytes) -> "Request":
        head_fmt = "<iiiiiddiiiHHHH"
        head_size = struct.calcsize(head_fmt)
        (rank, rtype, dtype, root, device, pre, post, psid, group_id,
         ndim, name_len, op_len, n_psr,
         n_spl) = struct.unpack_from(head_fmt, data)
        off = head_size
        shape = struct.unpack_from(f"<{ndim}q", data, off)
        off += 8 * ndim
        name = data[off:off + name_len].decode()
        off += name_len
        op = data[off:off + op_len].decode()
        off += op_len
        psr = struct.unpack_from(f"<{n_psr}i", data, off)
        off += 4 * n_psr
        spl = struct.unpack_from(f"<{n_spl}q", data, off)
        return cls(request_rank=rank, request_type=RequestType(rtype),
                   tensor_name=name, tensor_shape=tuple(shape),
                   tensor_type=DataType(dtype), root_rank=root,
                   device=device, prescale_factor=pre, postscale_factor=post,
                   process_set_id=psid, reduce_op=op,
                   process_set_ranks=tuple(psr), group_id=group_id,
                   splits=tuple(spl))


@dataclass
class Response:
    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    tensor_type: DataType = DataType.FLOAT32
    error_message: str = ""
    devices: List[int] = field(default_factory=list)
    # For allgather: per-rank first-dimension sizes; for alltoall: recv
    # splits (reference: message.h Response::tensor_sizes semantics).
    tensor_sizes: List[int] = field(default_factory=list)
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    process_set_id: int = 0
    root_rank: int = -1
    reduce_op: str = "Sum"
    last_joined_rank: int = -1
    # Per-tensor shapes aligned with tensor_names, so joined (departed)
    # ranks can substitute correctly-shaped zeros (JoinOp semantics,
    # reference collective_operations.h:259-276).
    tensor_shapes: List[Tuple[int, ...]] = field(default_factory=list)
    process_set_ranks: Tuple[int, ...] = ()
    # Coordinator-assigned response-cache bit per tensor (aligned with
    # tensor_names; -1 or empty = uncached).  The coordinator owns bit
    # assignment, so workers never have to agree on cache eviction order
    # (unlike the reference, where identical LRU caches are maintained by
    # symmetric bitvector sync — response_cache.h:107-169).
    cache_bits: List[int] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        err_b = self.error_message.encode()
        op_b = self.reduce_op.encode()
        names_b = [n.encode() for n in self.tensor_names]
        psr = self.process_set_ranks
        bits = self.cache_bits
        head = struct.pack(
            "<iiddiiiHIHHHHH", int(self.response_type),
            int(self.tensor_type),
            self.prescale_factor, self.postscale_factor,
            self.process_set_id, self.root_rank, self.last_joined_rank,
            len(names_b), len(self.tensor_sizes), len(err_b), len(op_b),
            len(self.tensor_shapes), len(psr), len(bits))
        parts = [head]
        for nb in names_b:
            parts.append(struct.pack("<H", len(nb)))
            parts.append(nb)
        parts.append(struct.pack(f"<{len(self.tensor_sizes)}q",
                                 *self.tensor_sizes))
        parts.append(err_b)
        parts.append(op_b)
        for shape in self.tensor_shapes:
            parts.append(struct.pack("<H", len(shape)))
            parts.append(struct.pack(f"<{len(shape)}q", *shape))
        parts.append(struct.pack(f"<{len(psr)}i", *psr))
        parts.append(struct.pack(f"<{len(bits)}i", *bits))
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Response":
        head_fmt = "<iiddiiiHIHHHHH"
        (rtype, dtype, pre, post, psid, root, last_joined, n_names,
         n_sizes, err_len, op_len, n_shapes, n_psr,
         n_bits) = struct.unpack_from(head_fmt, data)
        off = struct.calcsize(head_fmt)
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack_from("<H", data, off)
            off += 2
            names.append(data[off:off + ln].decode())
            off += ln
        sizes = list(struct.unpack_from(f"<{n_sizes}q", data, off))
        off += 8 * n_sizes
        err = data[off:off + err_len].decode()
        off += err_len
        op = data[off:off + op_len].decode()
        off += op_len
        shapes = []
        for _ in range(n_shapes):
            (nd,) = struct.unpack_from("<H", data, off)
            off += 2
            shapes.append(tuple(struct.unpack_from(f"<{nd}q", data, off)))
            off += 8 * nd
        psr = tuple(struct.unpack_from(f"<{n_psr}i", data, off))
        off += 4 * n_psr
        bits = list(struct.unpack_from(f"<{n_bits}i", data, off))
        return cls(response_type=ResponseType(rtype),
                   tensor_type=DataType(dtype), prescale_factor=pre,
                   postscale_factor=post, process_set_id=psid,
                   root_rank=root, last_joined_rank=last_joined,
                   tensor_names=names, tensor_sizes=sizes,
                   error_message=err, reduce_op=op, tensor_shapes=shapes,
                   process_set_ranks=psr, cache_bits=bits)


def pack_request_list(requests: List[Request],
                      shutdown: bool = False) -> bytes:
    parts = [struct.pack("<?I", shutdown, len(requests))]
    for r in requests:
        b = r.to_bytes()
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    return b"".join(parts)


def unpack_request_list(data: bytes) -> Tuple[List[Request], bool]:
    shutdown, n = struct.unpack_from("<?I", data)
    off = struct.calcsize("<?I")
    out = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        out.append(Request.from_bytes(data[off:off + ln]))
        off += ln
    return out, shutdown


def pack_response_list(responses: List[Response],
                       shutdown: bool = False) -> bytes:
    parts = [struct.pack("<?I", shutdown, len(responses))]
    for r in responses:
        b = r.to_bytes()
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    return b"".join(parts)


def unpack_response_list(data: bytes) -> Tuple[List[Response], bool]:
    shutdown, n = struct.unpack_from("<?I", data)
    off = struct.calcsize("<?I")
    out = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        out.append(Response.from_bytes(data[off:off + ln]))
        off += ln
    return out, shutdown


# ---------------------------------------------------------------------------
# Response-cache fast-path frames.  These replace full request/response
# lists in the steady state (the analog of the reference's bitvector
# cache sync, response_cache.cc:49-87 / controller.cc:81-236): a cache
# bit is 4 bytes on the wire vs ~100 for a full Request/Response.
# ---------------------------------------------------------------------------
def pack_bits(bits: List[int]) -> bytes:
    """CH (worker→coordinator cache hits) / EV (evictions) payload."""
    return struct.pack(f"<I{len(bits)}I", len(bits), *bits)


def unpack_bits(data: bytes) -> List[int]:
    (n,) = struct.unpack_from("<I", data)
    return list(struct.unpack_from(f"<{n}I", data, 4))


def pack_bit_batches(batches: List[List[int]]) -> bytes:
    """CB (coordinator→worker) payload: fused batches of cache bits, in
    execution order.  Each batch maps to ONE fused collective program."""
    parts = [struct.pack("<I", len(batches))]
    for batch in batches:
        parts.append(struct.pack(f"<I{len(batch)}I", len(batch), *batch))
    return b"".join(parts)


def unpack_bit_batches(data: bytes) -> List[List[int]]:
    (nb,) = struct.unpack_from("<I", data)
    off = 4
    out = []
    for _ in range(nb):
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        out.append(list(struct.unpack_from(f"<{n}I", data, off)))
        off += 4 * n
    return out
