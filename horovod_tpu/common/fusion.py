"""Fusion planning: batch matched responses into fused collectives.

The reference fuses same-type/same-dtype responses into one buffer up to
HOROVOD_FUSION_THRESHOLD bytes, with a look-ahead skip so one mismatched
dtype doesn't break a fusable run (reference: controller.cc:777-914,
FuseResponses, look-ahead at :826-848; threshold rounding for
hierarchical ops at :451-469).

On TPU the fused batch becomes ONE compiled XLA program (concat →
collective → split happen on-device in HBM, fused by XLA), so the fusion
plan doubles as the executable-cache key: stable plans mean compile-cache
hits — which is why deterministic ordering matters even more here than in
the reference (SURVEY §7 hard parts).
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

from typing import List

from . import metrics
from .message import Response, ResponseType, dtype_size


_FUSABLE = {ResponseType.ALLREDUCE, ResponseType.ADASUM,
            ResponseType.ALLGATHER, ResponseType.REDUCESCATTER}

_FUSED_TENSORS = metrics.histogram(
    "hvd_fusion_tensors_per_response",
    "Tensors batched into one fused response",
    bounds=metrics.COUNT_BUCKETS)
_FUSED_BYTES = metrics.histogram(
    "hvd_fusion_bytes",
    "Payload bytes per fused response (vs. HOROVOD_FUSION_THRESHOLD)",
    bounds=metrics.BYTE_BUCKETS)


def response_bytes(resp: Response, entry_sizes) -> int:
    """Total payload bytes of a response given per-tensor element
    counts.  ``entry_sizes`` is keyed by (process_set_id, name): the
    same name may be live on two process sets with different shapes."""
    total = 0
    for name in resp.tensor_names:
        total += entry_sizes[(resp.process_set_id, name)] * \
            dtype_size(resp.tensor_type)
    return total


def _can_fuse(a: Response, b: Response) -> bool:
    if a.response_type != b.response_type:
        return False
    if a.response_type not in _FUSABLE:
        return False
    return (a.tensor_type == b.tensor_type
            and a.process_set_id == b.process_set_id
            and a.prescale_factor == b.prescale_factor
            and a.postscale_factor == b.postscale_factor
            and a.reduce_op == b.reduce_op)


def _merge(a: Response, b: Response) -> Response:
    return Response(
        response_type=a.response_type,
        tensor_names=a.tensor_names + b.tensor_names,
        tensor_type=a.tensor_type,
        devices=a.devices,
        tensor_sizes=a.tensor_sizes + b.tensor_sizes,
        prescale_factor=a.prescale_factor,
        postscale_factor=a.postscale_factor,
        process_set_id=a.process_set_id,
        reduce_op=a.reduce_op,
        root_rank=a.root_rank,
        tensor_shapes=a.tensor_shapes + b.tensor_shapes,
        process_set_ranks=a.process_set_ranks,
    )


def _premerge_groups(responses: List[Response], group_ids) -> List[Response]:
    """Merge members of one grouped submission into a single response
    BEFORE threshold-bounded fusion, so a group is never split across
    compiled programs even when it exceeds the threshold (reference
    keeps groups together via the group table, controller.cc:199-223).
    Members of mixed dtype/op stay separate (they could not share one
    fused buffer anyway); order is anchored at each group's first
    member."""
    merged: List[Response] = []
    index = {}  # (group_id, fuse key) -> position in merged
    for resp in responses:
        gid = -1
        if resp.tensor_names and group_ids:
            gid = group_ids.get(
                (resp.process_set_id, resp.tensor_names[0]), -1)
        if gid < 0 or resp.response_type not in _FUSABLE:
            merged.append(resp)
            continue
        key = (gid, resp.response_type, resp.tensor_type,
               resp.process_set_id, resp.prescale_factor,
               resp.postscale_factor, resp.reduce_op)
        pos = index.get(key)
        if pos is None:
            index[key] = len(merged)
            merged.append(resp)
        else:
            merged[pos] = _merge(merged[pos], resp)
    return merged


def fuse_responses(responses: List[Response], entry_sizes,
                   threshold_bytes: int, group_ids=None) -> List[Response]:
    """Greedy fusion with look-ahead skip.

    ``entry_sizes`` maps tensor name → element count; ``group_ids``
    (optional) maps tensor name → grouped-submission id for group
    atomicity.  Responses that cannot fuse (broadcast, alltoall, errors,
    joins) pass through unchanged, preserving overall order determinism
    so every rank builds the identical plan.
    """
    out: List[Response] = []
    queue = _premerge_groups(responses, group_ids)
    while queue:
        base = queue.pop(0)
        if base.response_type not in _FUSABLE:
            out.append(base)
            continue
        acc_bytes = response_bytes(base, entry_sizes)
        fused = base
        skipped: List[Response] = []
        i = 0
        while i < len(queue):
            cand = queue[i]
            if _can_fuse(fused, cand):
                cand_bytes = response_bytes(cand, entry_sizes)
                if acc_bytes + cand_bytes <= threshold_bytes:
                    fused = _merge(fused, cand)
                    acc_bytes += cand_bytes
                    queue.pop(i)
                    continue
                else:
                    # Full — stop scanning, keep remaining order intact.
                    break
            else:
                # Look-ahead skip (reference controller.cc:826-848): a
                # response of a different dtype/type does not terminate
                # the scan; keep looking for fusable candidates behind it.
                i += 1
        out.append(fused)
    for resp in out:
        if resp.response_type in _FUSABLE and resp.tensor_names:
            _FUSED_TENSORS.observe(len(resp.tensor_names))
            try:
                _FUSED_BYTES.observe(response_bytes(resp, entry_sizes))
            except KeyError:
                pass  # caller passed a partial size map; skip bytes
    return out
