"""Fusion planning: batch matched responses into fused collectives.

The reference fuses same-type/same-dtype responses into one buffer up to
HOROVOD_FUSION_THRESHOLD bytes, with a look-ahead skip so one mismatched
dtype doesn't break a fusable run (reference: controller.cc:777-914,
FuseResponses, look-ahead at :826-848; threshold rounding for
hierarchical ops at :451-469).

On TPU the fused batch becomes ONE compiled XLA program (concat →
collective → split happen on-device in HBM, fused by XLA), so the fusion
plan doubles as the executable-cache key: stable plans mean compile-cache
hits — which is why deterministic ordering matters even more here than in
the reference (SURVEY §7 hard parts).
"""

from typing import List

from .message import Response, ResponseType, dtype_size


_FUSABLE = {ResponseType.ALLREDUCE, ResponseType.ADASUM,
            ResponseType.ALLGATHER, ResponseType.REDUCESCATTER}


def response_bytes(resp: Response, entry_sizes) -> int:
    """Total payload bytes of a response given per-tensor element counts."""
    total = 0
    for name in resp.tensor_names:
        total += entry_sizes[name] * dtype_size(resp.tensor_type)
    return total


def _can_fuse(a: Response, b: Response) -> bool:
    if a.response_type != b.response_type:
        return False
    if a.response_type not in _FUSABLE:
        return False
    return (a.tensor_type == b.tensor_type
            and a.process_set_id == b.process_set_id
            and a.prescale_factor == b.prescale_factor
            and a.postscale_factor == b.postscale_factor
            and a.reduce_op == b.reduce_op)


def fuse_responses(responses: List[Response], entry_sizes,
                   threshold_bytes: int) -> List[Response]:
    """Greedy fusion with look-ahead skip.

    ``entry_sizes`` maps tensor name → element count.  Responses that
    cannot fuse (broadcast, alltoall, errors, joins) pass through
    unchanged, preserving overall order determinism so every rank builds
    the identical plan.
    """
    out: List[Response] = []
    queue = list(responses)
    while queue:
        base = queue.pop(0)
        if base.response_type not in _FUSABLE:
            out.append(base)
            continue
        acc_bytes = response_bytes(base, entry_sizes)
        fused = base
        skipped: List[Response] = []
        i = 0
        while i < len(queue):
            cand = queue[i]
            if _can_fuse(fused, cand):
                cand_bytes = response_bytes(cand, entry_sizes)
                if acc_bytes + cand_bytes <= threshold_bytes:
                    fused = Response(
                        response_type=fused.response_type,
                        tensor_names=fused.tensor_names + cand.tensor_names,
                        tensor_type=fused.tensor_type,
                        devices=fused.devices,
                        tensor_sizes=fused.tensor_sizes + cand.tensor_sizes,
                        prescale_factor=fused.prescale_factor,
                        postscale_factor=fused.postscale_factor,
                        process_set_id=fused.process_set_id,
                        reduce_op=fused.reduce_op,
                        root_rank=fused.root_rank,
                        tensor_shapes=(fused.tensor_shapes +
                                       cand.tensor_shapes),
                        process_set_ranks=fused.process_set_ranks,
                    )
                    acc_bytes += cand_bytes
                    queue.pop(i)
                    continue
                else:
                    # Full — stop scanning, keep remaining order intact.
                    break
            else:
                # Look-ahead skip (reference controller.cc:826-848): a
                # response of a different dtype/type does not terminate
                # the scan; keep looking for fusable candidates behind it.
                i += 1
        out.append(fused)
    return out
