"""Gaussian-process regression with an RBF kernel.

The numpy re-derivation of the reference's Eigen implementation
(reference: common/optim/gaussian_process.{h,cc} (117+183) — RBF
kernel, cholesky solve, predictive mean/variance).  Kernel
hyperparameters (length scale, signal variance) are fixed per fit like
the reference; observation noise ``alpha`` regularizes the diagonal.
"""

from typing import Optional, Tuple

import numpy as np


class GaussianProcessRegressor:
    def __init__(self, alpha: float = 1e-8, length_scale: float = 1.0,
                 sigma_f: float = 1.0):
        self.alpha = alpha
        self.length_scale = length_scale
        self.sigma_f = sigma_f
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None
        self._alpha_vec: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """RBF: sigma_f^2 * exp(-||a-b||^2 / (2 l^2))."""
        sq = (np.sum(a ** 2, axis=1)[:, None] +
              np.sum(b ** 2, axis=1)[None, :] - 2 * a @ b.T)
        sq = np.maximum(sq, 0.0)
        return self.sigma_f ** 2 * np.exp(-0.5 * sq /
                                          self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        K = self.kernel(x, x)
        K[np.diag_indices_from(K)] += self.alpha
        self._L = np.linalg.cholesky(K)
        self._alpha_vec = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))
        self._x, self._y = x, yn
        return self

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (mean, std) of the posterior at x (denormalized)."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        if self._x is None:
            return (np.full(len(x), self._y_mean),
                    np.full(len(x), self.sigma_f * self._y_std))
        Ks = self.kernel(x, self._x)
        mean = Ks @ self._alpha_vec
        v = np.linalg.solve(self._L, Ks.T)
        var = self.sigma_f ** 2 - np.sum(v ** 2, axis=0)
        var = np.maximum(var, 1e-12)
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)
