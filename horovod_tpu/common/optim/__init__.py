"""Optimization utilities for the autotuner (reference:
horovod/common/optim/ — Gaussian-process regression + Bayesian
optimization with Expected Improvement)."""

from .gaussian_process import GaussianProcessRegressor
from .bayesian_optimization import BayesianOptimization

__all__ = ["GaussianProcessRegressor", "BayesianOptimization"]
