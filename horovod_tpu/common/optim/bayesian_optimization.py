"""Bayesian optimization with Expected Improvement.

Reference: common/optim/bayesian_optimization.{h,cc} (114+194) —
``AddSample``/``NextSample``/``ExpectedImprovement``: a GP is fit to
(params, score) samples and the next trial point maximizes EI.  The
reference maximizes EI with LBFGS over random restarts; here EI is
maximized over a dense random candidate set refined by L-BFGS-B
(scipy), which is equivalent in practice for the 1-3 dimensional knob
spaces involved.
"""

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .gaussian_process import GaussianProcessRegressor


def _norm_cdf(z):
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


class BayesianOptimization:
    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 gp_noise: float = 0.8, xi: float = 0.01, seed: int = 0):
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.dim = len(bounds)
        self.xi = xi
        self._rng = np.random.RandomState(seed)
        # Normalize inputs to [0,1]^d for a sane fixed length scale.
        self._gp = GaussianProcessRegressor(alpha=gp_noise ** 2,
                                            length_scale=0.3,
                                            sigma_f=1.0)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []

    def _to_unit(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x, dtype=np.float64) - lo) / (hi - lo)

    def _from_unit(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u) * (hi - lo)

    def add_sample(self, x, y: float):
        self._x.append(self._to_unit(x))
        self._y.append(float(y))
        self._gp.fit(np.vstack(self._x), np.asarray(self._y))

    def expected_improvement(self, u: np.ndarray) -> np.ndarray:
        mean, std = self._gp.predict(np.atleast_2d(u))
        best = max(self._y) if self._y else 0.0
        imp = mean - best - self.xi
        z = imp / std
        return imp * _norm_cdf(z) + std * _norm_pdf(z)

    def next_sample(self) -> np.ndarray:
        """The params (original scale) maximizing EI."""
        if not self._x:
            return self._from_unit(self._rng.uniform(size=self.dim))
        cands = self._rng.uniform(size=(256, self.dim))
        ei = self.expected_improvement(cands)
        u0 = cands[int(np.argmax(ei))]
        try:
            from scipy.optimize import minimize
        except ImportError:
            minimize = None
        if minimize is not None:
            res = minimize(
                lambda u: -self.expected_improvement(u[None, :])[0],
                u0, bounds=[(0.0, 1.0)] * self.dim, method="L-BFGS-B")
            if res.success:
                u0 = res.x
        return self._from_unit(np.clip(u0, 0.0, 1.0))

    @property
    def best(self) -> Optional[Tuple[np.ndarray, float]]:
        if not self._y:
            return None
        i = int(np.argmax(self._y))
        return self._from_unit(self._x[i]), self._y[i]
