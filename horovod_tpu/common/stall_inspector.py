"""Stall inspector: detect ranks that fail to submit matching tensors.

Mirrors the reference stall inspector (reference: stall_inspector.{h,cc}:
rank-0 warns when some ranks submitted a tensor and others have not for
>60 s (:74-80), optionally shuts down after
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, and invalidates stalled cached
tensors so they renegotiate).
"""

import logging
import time
from typing import Dict, List, Set, Tuple

from . import flight_recorder as _fr
from . import metrics
from . import profiler as _prof

logger = logging.getLogger("horovod_tpu.stall")

_STALL_WARNINGS = metrics.counter(
    "hvd_stall_warnings_total",
    "Tensors that crossed the stall warning threshold")


class StallInspector:
    def __init__(self, warning_time_s: float = 60.0,
                 shutdown_time_s: float = 0.0, world_size: int = 1):
        self.warning_time_s = warning_time_s
        self.shutdown_time_s = shutdown_time_s
        self.world_size = world_size
        # tensor name -> (first seen ts, set of ranks that reported)
        self._uncompleted: Dict[str, Tuple[float, Set[int]]] = {}
        self._warned: Set[str] = set()
        # Optional live-straggler hook (common/straggler.py): when the
        # coordinator's scorer is armed on this rank, warnings name
        # the current top straggler so "everyone blocked on a slow
        # rank" is distinguishable from "a rank died / coordinator
        # wedged" without a postmortem.
        self._straggler_provider = None
        # Optional why-is-it-slow hook (common/profiler.py): when the
        # coordinator also holds per-rank profile digests, the warning
        # names the implicated rank's dominant frame — root cause, not
        # just attribution.
        self._root_cause_provider = None

    def set_straggler_provider(self, fn):
        """``fn() -> Optional[(rank, score)]`` — wired by the runtime
        on the rank hosting the Python coordinator."""
        self._straggler_provider = fn

    def set_root_cause_provider(self, fn):
        """``fn(rank) -> Optional[str]`` — a one-clause root cause for
        the given rank ("failpoints:maybe_fail (submit lane, 72% of
        samples)"), from the coordinator's profile digests."""
        self._root_cause_provider = fn

    def _root_cause_note(self, rank: int) -> str:
        if self._root_cause_provider is None:
            return ""
        try:
            cause = self._root_cause_provider(rank)
        except Exception:
            return ""
        return (", dominant frame: %s" % cause) if cause else ""

    def _straggler_note(self) -> str:
        if self._straggler_provider is None:
            return ""
        try:
            top = self._straggler_provider()
        except Exception:
            return ""
        if top is None:
            return ""
        return (". Current top straggler: rank %d (score %.1f%s) — if "
                "it is among the waiting ranks, they are slow, not "
                "dead" % (top[0], top[1],
                          self._root_cause_note(top[0])))

    def record_uncached_tensor(self, name: str, rank: int):
        now = time.monotonic()
        ts, ranks = self._uncompleted.get(name, (now, set()))
        ranks.add(rank)
        self._uncompleted[name] = (ts, ranks)

    def record_cached_tensor(self, name: str):
        # Cached tensors bypass negotiation; still track timestamps so a
        # rank that stops submitting a cached tensor is caught.
        self.record_uncached_tensor(name, -1)

    def remove(self, name: str):
        self._uncompleted.pop(name, None)
        self._warned.discard(name)

    def check(self) -> List[str]:
        """Returns tensor names to invalidate from the response cache;
        logs warnings; raises on shutdown threshold."""
        now = time.monotonic()
        invalidate = []
        stalled_msgs = []
        for name, (ts, ranks) in self._uncompleted.items():
            age = now - ts
            if age > self.warning_time_s and name not in self._warned:
                missing = sorted(set(range(self.world_size)) -
                                 {r for r in ranks if r >= 0})
                stalled_msgs.append(
                    f"{name} [ready: {sorted(r for r in ranks if r >= 0)}, "
                    f"waiting: {missing}]")
                self._warned.add(name)
                invalidate.append(name)
                _STALL_WARNINGS.inc()
            if self.shutdown_time_s > 0 and age > self.shutdown_time_s:
                raise RuntimeError(
                    f"Stalled tensor {name!r} exceeded shutdown threshold "
                    f"({self.shutdown_time_s}s); aborting (set "
                    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS=0 to disable).")
        if stalled_msgs:
            # Flight-recorder attribution: what the implicated tensors
            # last DID (submit/frame/replay events from the black-box
            # ring), not just which ranks are waiting.
            recent = _fr.recent_for_tensors(invalidate) \
                if _fr.ENABLED and invalidate else []
            if _prof.ENABLED:
                # Why-is-it-slow: freeze the profiler's last window at
                # the moment the stall surfaced (triggered capture —
                # throttled, cold warning path).
                _prof.trigger_capture(
                    "stall", stalled_msgs[0][:120])
            logger.warning(
                "One or more tensors were submitted to be reduced/gathered "
                "but some ranks have not yet submitted them. Stalled ops: %s%s%s",
                "; ".join(stalled_msgs),
                self._straggler_note(),
                (". Last recorder events: %s" % recent) if recent
                else "")
        return invalidate
