"""Chrome-tracing timeline writer.

Mirrors the reference's Horovod Timeline (reference: timeline.{h,cc}:
TimelineWriter with a dedicated writer thread fed by a lock-free SPSC
queue :48-100; per-tensor state machine NEGOTIATING → TOP_LEVEL →
ACTIVITY :106-154; written on the coordinator rank only,
operations.cc:422-425; format documented in docs/timeline.rst).

Python implementation uses a queue.SimpleQueue (lock-free fast path on
CPython) + daemon writer thread.  The output is standard chrome://tracing
JSON, one async span per tensor keyed by a stable "tid" so collectives
stack per tensor name.  XLA device-side profiling is delegated to
``jax.profiler`` (see ``start_xla_trace``) — host spans here, device
timeline there, matching the GPU event-queue split in the reference.
"""

import json
import logging
import os
import queue
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("horovod_tpu.timeline")

# Activity names, matching the reference span vocabulary (common.h:32-62).
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
NEGOTIATE_ALLTOALL = "NEGOTIATE_ALLTOALL"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
FUSE_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
UNFUSE_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_ALLREDUCE = "XLA_ALLREDUCE"
XLA_ALLGATHER = "XLA_ALLGATHER"
XLA_BROADCAST = "XLA_BROADCAST"
XLA_ALLTOALL = "XLA_ALLTOALL"
XLA_REDUCESCATTER = "XLA_REDUCESCATTER"
XLA_COMPILE = "XLA_COMPILE"
ADASUM_VHDD = "ADASUM_VHDD"
QUEUE = "QUEUE"


class TimelineWriter:
    def __init__(self, file_path: str):
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._file_path = file_path
        self._active = True
        self._thread = threading.Thread(
            target=self._run, name="hvd-timeline-writer", daemon=True)
        self._thread.start()

    def enqueue(self, record: dict):
        if self._active:
            self._queue.put(record)

    def _run(self):
        try:
            os.makedirs(os.path.dirname(os.path.abspath(self._file_path)),
                        exist_ok=True)
            with open(self._file_path, "w") as f:
                f.write("[\n")
                first = True
                while True:
                    rec = self._queue.get()
                    if rec is None:
                        break
                    if not first:
                        f.write(",\n")
                    f.write(json.dumps(rec))
                    first = False
                    f.flush()
                f.write("\n]\n")
        except Exception:
            # Without this flip a writer that cannot open (or keep
            # writing) its file dies silently while enqueue() keeps
            # growing the queue unbounded for the rest of the run.
            self._active = False
            logger.warning(
                "timeline writer failed for %s; timeline recording "
                "disabled", self._file_path, exc_info=True)

    def close(self):
        if self._active:
            self._active = False
            self._queue.put(None)
            self._thread.join(timeout=5.0)


class Timeline:
    """Per-tensor span state machine emitting chrome-tracing events."""

    def __init__(self, file_path: str, rank: int = 0,
                 mark_cycles: bool = False):
        self.rank = rank
        self.mark_cycles = mark_cycles
        self.writer = TimelineWriter(file_path) if rank == 0 else None
        self._tids: Dict[str, int] = {}
        self._next_tid = 1
        self._lock = threading.Lock()
        self._start = time.perf_counter()

    def _ts_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def _tid(self, tensor_name: str) -> int:
        with self._lock:
            tid = self._tids.get(tensor_name)
            if tid is None:
                tid = self._next_tid
                self._next_tid += 1
                self._tids[tensor_name] = tid
                if self.writer:
                    self.writer.enqueue({
                        "name": "thread_name", "ph": "M", "pid": 0,
                        "tid": tid, "args": {"name": tensor_name}})
            return tid

    def negotiate_start(self, tensor_name: str, request_type: str):
        self._emit_begin(tensor_name, f"NEGOTIATE_{request_type}")

    def negotiate_rank_ready(self, tensor_name: str, rank: int):
        if self.writer:
            self.writer.enqueue({
                "name": str(rank), "ph": "i", "pid": 0,
                "tid": self._tid(tensor_name), "ts": self._ts_us(),
                "s": "t"})

    def negotiate_end(self, tensor_name: str):
        self._emit_end(tensor_name)

    def start_activity(self, tensor_name: str, activity: str):
        self._emit_begin(tensor_name, activity)

    def end_activity(self, tensor_name: str):
        self._emit_end(tensor_name)

    def counter(self, name: str, values: Dict[str, float]):
        """Chrome-tracing counter event ("ph":"C"): renders as a
        stacked-area track alongside the spans, so live registry values
        (queue depth, fused bytes) line up with negotiation/execution
        activity in the same trace."""
        if self.writer:
            self.writer.enqueue({
                "name": name, "ph": "C", "pid": 0, "tid": 0,
                "ts": self._ts_us(), "args": dict(values)})

    def instant(self, name: str):
        """Process-scoped instant event (steady-state replay
        enter/exit marks and similar one-shot state flips)."""
        if self.writer:
            self.writer.enqueue({
                "name": name, "ph": "i", "pid": 0, "tid": 0,
                "ts": self._ts_us(), "s": "p"})

    def mark_cycle_start(self):
        if self.writer and self.mark_cycles:
            self.writer.enqueue({
                "name": "CYCLE_START", "ph": "i", "pid": 0, "tid": 0,
                "ts": self._ts_us(), "s": "g"})

    def _emit_begin(self, tensor_name: str, name: str):
        if self.writer:
            self.writer.enqueue({
                "name": name, "ph": "B", "pid": 0,
                "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def _emit_end(self, tensor_name: str):
        if self.writer:
            self.writer.enqueue({
                "ph": "E", "pid": 0, "tid": self._tid(tensor_name),
                "ts": self._ts_us()})

    def close(self):
        if self.writer:
            self.writer.close()
            self.writer = None


def start_xla_trace(log_dir: str):
    """Start the XLA device profiler alongside the host timeline; view in
    TensorBoard/XProf.  Complements host spans the way the reference's GPU
    event queue does (ops/gpu_operations.h:110-119)."""
    import jax
    jax.profiler.start_trace(log_dir)


def stop_xla_trace():
    import jax
    jax.profiler.stop_trace()
