"""Tensor table + pending-request queue shared between the caller threads
and the background runtime.

Mirrors the reference tensor queue (reference: common/tensor_queue.{h,cc}:
mutex-guarded name → TensorTableEntry map + pending Request queue, with
duplicate-name rejection per common.h:165-168 and a shutdown flush that
fails every outstanding callback).
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .exceptions import DuplicateTensorNameError
from .message import Request


@dataclass
class TensorTableEntry:
    tensor_name: str
    tensor: Any                       # payload (jax/numpy array)
    callback: Callable                # fn(status_ok, result_or_error)
    root_rank: int = -1
    device: int = 0
    process_set_id: int = 0
    # Optional second payload (e.g. alltoall splits).
    splits: Any = None
    context: dict = field(default_factory=dict)


class SHUT_DOWN_ERROR(RuntimeError):
    pass


class TensorQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[str, TensorTableEntry] = {}
        self._pending: List[Request] = []

    def add(self, request: Request, entry: TensorTableEntry):
        with self._lock:
            key = (entry.tensor_name, entry.process_set_id)
            tkey = f"{entry.process_set_id}:{entry.tensor_name}"
            if tkey in self._table:
                raise DuplicateTensorNameError(
                    f"Duplicate tensor name {entry.tensor_name!r} submitted; "
                    "a previous collective with this name has not completed. "
                    "This usually means ranks are running different graphs.")
            self._table[tkey] = entry
            self._pending.append(request)

    def add_multi(self, requests: List[Request],
                  entries: List[TensorTableEntry]):
        with self._lock:
            for e in entries:
                tkey = f"{e.process_set_id}:{e.tensor_name}"
                if tkey in self._table:
                    raise DuplicateTensorNameError(
                        f"Duplicate tensor name {e.tensor_name!r} in group.")
            for r, e in zip(requests, entries):
                tkey = f"{e.process_set_id}:{e.tensor_name}"
                self._table[tkey] = e
                self._pending.append(r)

    def add_entry_only(self, entry: TensorTableEntry):
        """Table insert without queueing the request — the inline
        cache-hit path sends its own CH frame from the caller thread,
        so the entry must be resolvable by the dispatch thread but the
        request must never reach the negotiation queue."""
        with self._lock:
            tkey = f"{entry.process_set_id}:{entry.tensor_name}"
            if tkey in self._table:
                raise DuplicateTensorNameError(
                    f"Duplicate tensor name {entry.tensor_name!r} "
                    "submitted; a previous collective with this name "
                    "has not completed. This usually means ranks are "
                    "running different graphs.")
            self._table[tkey] = entry

    def queue_request(self, request: Request):
        """Queue a request whose entry is already in the table (the
        inline path falling back to negotiation on a cache miss)."""
        with self._lock:
            self._pending.append(request)

    def queue_requests(self, requests: List[Request]):
        """Bulk variant of :meth:`queue_request` (steady-state replay
        exiting with a partially-submitted batch): one lock round for
        the whole flush, preserving submission order."""
        with self._lock:
            self._pending.extend(requests)

    def pop_pending(self) -> List[Request]:
        """Drain the pending-request queue (one negotiation cycle's worth)."""
        with self._lock:
            pending, self._pending = self._pending, []
            return pending

    def push_back(self, requests: List[Request]):
        """Return unserviced requests to the queue head (e.g. when the
        coordinator has not matched them yet)."""
        with self._lock:
            self._pending = requests + self._pending

    def get_entry(self, name: str, process_set_id: int = 0
                  ) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.get(f"{process_set_id}:{name}")

    def pop_entry(self, name: str, process_set_id: int = 0
                  ) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.pop(f"{process_set_id}:{name}", None)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def outstanding(self) -> int:
        with self._lock:
            return len(self._table)

    def shutdown_flush(self, error: Optional[Exception] = None):
        """Fail every outstanding callback (reference: tensor_queue
        finalize → SHUT_DOWN_ERROR)."""
        err = error or SHUT_DOWN_ERROR(
            "Horovod-TPU has been shut down; outstanding collective "
            "was cancelled.")
        with self._lock:
            entries = list(self._table.values())
            self._table.clear()
            self._pending.clear()
        for e in entries:
            try:
                e.callback(False, err)
            except Exception:
                pass
