"""Background runtime: the per-process coordination thread.

Mirrors the reference background loop (reference: operations.cc:356-585
BackgroundThreadLoop / RunLoopOnce :587-645 / PerformOperation :253-332):
one thread per process owns all communication — it drains the tensor
queue every cycle, runs negotiation through the controller, executes the
fused responses on the data-plane backend, and fires completion
callbacks.

TPU-specific deltas from the reference:
  * the data plane executes compiled XLA programs (dispatch is async on
    the JAX runtime's own stream — no finalizer thread pool needed; we
    only block a worker thread on `.block_until_ready` when a caller
    synchronizes);
  * the response cache doubles as the compiled-executable cache key
    (SURVEY §7), so cache hits skip negotiation AND recompilation.
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import itertools
import logging
import threading
import time
from typing import Dict, List, Optional

from . import failpoints as _fp
from . import flight_recorder as _fr
from . import metrics
from . import slo as _slo
from . import straggler as _sg
from . import timeline as tl
from .controller import LoopbackController
from .message import (Request, RequestType, Response, ResponseType)
from .replay import SteadyStateReplay
from .stall_inspector import StallInspector
from .tensor_queue import TensorQueue, TensorTableEntry

logger = logging.getLogger("horovod_tpu.runtime")

_CYCLES = metrics.counter(
    "hvd_cycles_total", "Background cycle-loop iterations")
_CYCLE_SECONDS = metrics.histogram(
    "hvd_cycle_seconds",
    "Work-cycle duration (queue drain through response dispatch)")
_QUEUE_DEPTH = metrics.gauge(
    "hvd_queue_depth", "Tensor-table entries awaiting completion")
_SUBMIT_LATENCY = metrics.histogram(
    "hvd_submit_latency_seconds",
    "submit() to completion-callback latency per tensor")
_RESPONSES = metrics.counter(
    "hvd_responses_dispatched_total",
    "Responses executed on this rank, by collective type")
_JOIN_ZEROS = metrics.counter(
    "hvd_join_zero_substituted_total",
    "Zero tensors substituted for collectives this joined rank "
    "did not submit")


def _latency_wrapped(cb, collector=None):
    """Stamp submit time into the completion callback so the
    submit-to-callback latency histogram sees every path (negotiated,
    inline cache hit, error flush)."""
    t0 = time.perf_counter()

    def wrapped(ok, result):
        dt = time.perf_counter() - t0
        _SUBMIT_LATENCY.observe(dt)
        if _sg.ENABLED and collector is not None:
            # Straggler observatory: the submit→executed e2e phase
            # EWMA (published into MR frames by the controller).
            # Disabled cost: this one attribute check.
            collector.note_latency(dt)
        return cb(ok, result)
    return wrapped


class BackgroundRuntime:
    def __init__(self, state):
        self.state = state
        self.tensor_queue = TensorQueue()
        # Cross-rank group ids for grouped submissions (group-atomic
        # fusion).  Monotonic per process; ranks agree because grouped
        # collectives are submitted in the same order everywhere (the
        # same ordering contract auto-generated tensor names rely on).
        self._group_counter = itertools.count()
        self.stall_inspector = StallInspector(
            warning_time_s=state.knobs.stall_warning_time_s,
            shutdown_time_s=state.knobs.stall_shutdown_time_s,
            world_size=state.rank_info.size,
        ) if not state.knobs.stall_check_disable else None
        self.timeline = None
        # Per-runtime phase-time EWMAs for the straggler observatory
        # (common/straggler.py): fed from the hot paths behind the
        # ENABLED gate, published into MR metrics frames by the
        # controller (rank-labeled, so relay pre-aggregation carries
        # every rank's summary through intact).
        self.phase_collector = _sg.PhaseCollector()
        self.controller = self._make_controller()
        if hasattr(self.controller, "set_phase_collector"):
            self.controller.set_phase_collector(self.phase_collector)
        if self.stall_inspector is not None:
            # On the rank hosting the Python coordinator, local stall
            # warnings also name the current top straggler — "everyone
            # blocked on rank 3" reads differently from "coordinator
            # wedged" (common/straggler.py).  getattr chains resolve
            # to None everywhere else (loopback, workers, native).
            top = getattr(getattr(self.controller, "server", None),
                          "straggler_top", None)
            if top is not None:
                self.stall_inspector.set_straggler_provider(top)
            # And WHY it is slow: the coordinator's per-rank profile
            # digests (common/profiler.py) name the dominant frame of
            # the implicated rank in the same warning line.
            rc = getattr(getattr(self.controller, "server", None),
                         "profile_root_cause", None)
            if rc is not None:
                self.stall_inspector.set_root_cause_provider(rc)
        self._shutdown = threading.Event()
        self._wake = threading.Event()
        # Direct dispatch: the controller's recv thread EXECUTES each
        # response the moment its frame decodes (no queue hop to this
        # thread — on a 1-core host that handoff is a context switch,
        # a fixed ~0.1-0.2 ms per op).  The background thread then only
        # services submissions/negotiation.  Ordering still follows the
        # coordinator's broadcast order: the recv loop is the single
        # sequential consumer of the socket.
        self._inline = False
        if hasattr(self.controller, "set_response_callback"):
            self.controller.set_response_callback(self._dispatch_response)
            self._inline = hasattr(self.controller,
                                   "try_inline_cache_hit")
        elif hasattr(self.controller, "set_receive_callback"):
            self.controller.set_receive_callback(self._wake.set)
        # Steady-state replay (common/replay.py): negotiation-free
        # execution of converged cycles.  Networked worlds only (a
        # loopback world has no round-trip to skip).  Autotune no
        # longer disables replay outright: while a tuning search is
        # live (HOROVOD_TUNE / HOROVOD_AUTOTUNE, not yet frozen) the
        # tracker is HELD — it observes but refuses entry, labeled
        # hvd_steady_state_exits{reason="tuning"} — and the
        # freeze/convergence PA announcement releases it, so the
        # lifecycle is warmup -> freeze -> replay (docs/autotune.md).
        # A reloaded tuned profile means the search already ran:
        # replay is free from the first cycle.
        self.replay: Optional[SteadyStateReplay] = None
        # Worker-side tuning lifecycle bit, tracked on the runtime
        # itself (not only via the replay tracker — which may not
        # exist, e.g. HOROVOD_STEADY_STATE_REPLAY=0): flipped by the
        # tuning_active field of PA announcements; read by
        # hvd.tune_status().
        self.tuning_active = (state.knobs.tune or
                              state.knobs.autotune) and \
            not state.knobs.tune_profile_loaded
        if self._inline and state.knobs.replay_enabled:
            self.replay = SteadyStateReplay(
                self, warmup_cycles=state.knobs.replay_warmup_cycles)
            if self.tuning_active:
                self.replay.set_tuning(True)
            if hasattr(self.controller, "set_replay_observer"):
                self.controller.set_replay_observer(self.replay)
        # Request coalescing (tunable): when on (default), the inline
        # fast path is taken only from an IDLE table so async bursts
        # drain as one coalesced CH/RQ frame per kind; off = every
        # eligible submission goes inline immediately (one frame per
        # op — lower latency for strictly synchronous loops, more
        # frames for bursty ones).  The tuner explores both.
        self._coalesce = state.knobs.request_coalescing
        if hasattr(self.controller, "set_params_hook"):
            self.controller.set_params_hook(self._apply_tuned_params)
        self._thread: Optional[threading.Thread] = None
        self._cycle_time_s = state.knobs.cycle_time_ms / 1000.0
        self._entry_sizes: Dict[tuple, int] = {}  # (psid, name)
        self._joined = False
        self._error: Optional[Exception] = None
        # Called once when a fatal control-plane error surfaces (e.g.
        # coordinator connection lost in an elastic resize): lets
        # side-band machinery unblock FAST — the TF graph-collective
        # layer aborts in-flight CollectiveReduceV2 waits so the user
        # thread unwinds immediately instead of riding out the
        # collective timeout while peers tear the world down.
        self._fatal_listeners = []
        self._fatal_fired = False
        self._dispatch_disabled = False
        # Serializes recv-thread direct dispatch against quiesce():
        # backend.close() must never overlap a running
        # _perform_operation (the ring backend has its own fusion-lock
        # serialization, but the XLA mesh backend has none).
        self._dispatch_lock = threading.Lock()
        if hasattr(self.controller, "set_broken_callback"):
            self.controller.set_broken_callback(self._on_fatal)

    def set_joined(self, flag: bool):
        """While joined, this rank substitutes zeros for collectives it
        did not submit (JoinOp, reference collective_operations.h:259)."""
        self._joined = flag
        if flag and self.replay is not None:
            # Join changes every cached response's validity (zeros get
            # substituted for this rank); negotiate until re-converged.
            self.replay.note_disruption("join")

    def wake(self):
        """Wake the background cycle (replay exit flushes its partial
        batch into the negotiation queue and needs a cycle now)."""
        self._wake.set()

    def _apply_tuned_params(self, params: dict):
        """Adopt tuned worker knobs announced through a PA frame
        (horovod_tpu/tune).  Runs at the frame's position in the
        response stream — identical on every rank — so no two ranks
        ever run different knobs for the same cycle."""
        knobs = self.state.knobs
        if "cycle_time_ms" in params:
            knobs.cycle_time_ms = float(params["cycle_time_ms"])
            self._cycle_time_s = knobs.cycle_time_ms / 1000.0
        if "coalesce" in params:
            self._coalesce = bool(params["coalesce"])
            knobs.request_coalescing = self._coalesce
        if "tuning_active" in params:
            self.tuning_active = bool(params["tuning_active"])
        replay = self.replay
        if replay is not None:
            if "replay_warmup" in params:
                knobs.replay_warmup_cycles = int(params["replay_warmup"])
                replay.set_warmup(knobs.replay_warmup_cycles)
            if "tuning_active" in params:
                replay.set_tuning(bool(params["tuning_active"]))

    def _make_controller(self):
        if self.state.rank_info.size == 1:
            return LoopbackController(self.state)
        from .controller_net import NetworkController
        return NetworkController(self.state)

    # ------------------------------------------------------------------
    # submission API (called from user/framework threads)
    # ------------------------------------------------------------------
    def submit(self, request: Request, entry: TensorTableEntry):
        if self._error is not None:
            raise self._error
        if _fp.ENABLED:
            # Failpoint site: eager submission, on the caller's thread.
            # delay() models framework-side jitter; error() a rank that
            # dies mid-step (the chaos harness crashes ranks here).
            _fp.maybe_fail("runtime.submit",
                           rank=self.state.rank_info.rank)
        if _fr.ENABLED:
            # Flight-recorder site (the per-collective record the NCCL
            # flight recorder keeps): disabled cost is this ONE
            # attribute check, pinned by tests/test_flight_recorder.py.
            _fr.record(_fr.SUBMIT, rank=self.state.rank_info.rank,
                       name=request.tensor_name,
                       type=request.request_type.name)
        entry.callback = _latency_wrapped(entry.callback,
                                          self.phase_collector)
        nelem = 1
        for d in request.tensor_shape:
            nelem *= d
        self._entry_sizes[(request.process_set_id,
                           request.tensor_name)] = nelem
        replay = self.replay
        if replay is not None and not self._joined:
            if replay.active and replay.eligible(request):
                # Frozen schedule: match + execute locally, no wire
                # traffic.  False = replay just exited (unseen tensor,
                # signature change, armed failpoint, ...) — fall
                # through; THIS request rides the negotiation round.
                if replay.replay_submit(request, entry):
                    return
            elif replay.eligible(request):
                if replay.observe_submit(request) and \
                        replay.replay_submit(request, entry):
                    return
            else:
                # Joins/barriers/allgathers/alltoalls break cycle
                # convergence (see replay.py for why).
                replay.note_disruption(
                    request.request_type.name.lower())
        if self.timeline:
            self.timeline.negotiate_start(
                request.tensor_name, request.request_type.name)
        # Inline fast path only from an IDLE table: during an async
        # burst (N grads submitted before any completes) the first op
        # goes inline and the rest queue, so the background drain sends
        # them as ONE coalesced CH/RQ frame per kind instead of one
        # frame per tensor — look-ahead fusion then sees whole cycles
        # (r05 measured one RQ frame per tensor).  Synchronous loops
        # always see an idle table, so the tiny-op floor is unchanged.
        if self._inline and request.group_id < 0 and not self._joined \
                and (not self._coalesce or
                     self.tensor_queue.outstanding() == 0):
            # Inline cache-hit fast path: entry lands in the table
            # FIRST (the recv thread may dispatch the response
            # immediately), then the CH frame goes out on THIS thread
            # — no background wake.  Bit/request order on the socket
            # is per-rank arbitrary by protocol (the coordinator
            # counts per tensor), so racing the background thread's
            # own sends under the controller's send lock is safe.
            self.tensor_queue.add_entry_only(entry)
            # Stall bookkeeping BEFORE the frame goes out: once the CH
            # frame is sent the recv thread may dispatch and remove()
            # at any moment — recording afterwards would resurrect a
            # completed tensor and later trip a spurious stall
            # shutdown.
            if self.stall_inspector is not None:
                self.stall_inspector.record_uncached_tensor(
                    request.tensor_name, request.request_rank)
            try:
                sent = self.controller.try_inline_cache_hit(request)
            except Exception as e:
                # Mirror the background loop's error contract: fail
                # every outstanding callback (including this entry)
                # and surface to future submitters — otherwise the
                # stale table entry turns the real connectivity error
                # into DuplicateTensorNameError on retry.
                self._error = e
                self.tensor_queue.shutdown_flush(e)
                raise
            if sent:
                return
            # Cache miss: fall back to the negotiation queue.
            self.tensor_queue.queue_request(request)
            self._wake.set()
            return
        self.tensor_queue.add(request, entry)
        self._wake.set()

    def submit_group(self, requests: List[Request],
                     entries: List[TensorTableEntry]):
        if self._error is not None:
            raise self._error
        if self.replay is not None:
            # Grouped submissions negotiate (group atomicity is the
            # coordinator's job); they also invalidate a frozen cycle.
            self.replay.note_disruption("group")
        group_id = next(self._group_counter)
        for entry in entries:
            entry.callback = _latency_wrapped(entry.callback,
                                              self.phase_collector)
        for request in requests:
            request.group_id = group_id
            nelem = 1
            for d in request.tensor_shape:
                nelem *= d
            self._entry_sizes[(request.process_set_id,
                               request.tensor_name)] = nelem
            if self.timeline:
                # Grouped tensors get the same negotiation span as
                # single submissions — dispatch closes one span per
                # tensor name, so every name must open one here.
                self.timeline.negotiate_start(
                    request.tensor_name, request.request_type.name)
        self.tensor_queue.add_multi(requests, entries)
        self._wake.set()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-background", daemon=True)
        self._thread.start()

    def stop_background(self):
        """Halt the cycle loop WITHOUT detaching from the coordinator
        — teardown sequencing needs the controller attachment as a
        liveness signal (see basics.shutdown: the rank-0 coordinator
        drain-waits on attachments, which lets non-leader ranks
        disconnect their jax coordination client while the leader is
        still alive; a leader going down under an attached client is
        process-fatal in jax)."""
        self._shutdown.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def quiesce(self):
        """Stop executing NEW responses and fail outstanding
        callbacks, while keeping the controller attached (see
        stop_background).  Must precede backend teardown: the recv
        thread direct-dispatches responses, so without this a frame
        arriving mid-shutdown would execute against a closed/freed
        backend."""
        if self.replay is not None:
            # Exit replay BEFORE disabling dispatch so a final partial
            # batch flushes into the (about-to-be-failed) queue rather
            # than executing against a closing backend.
            self.replay.set_enabled(False)
        self.stop_background()
        self._dispatch_disabled = True
        # A dispatch that passed the disabled check before we set it
        # may still be running on the recv thread; taking the lock
        # waits it out so the caller can close the backend safely.
        # Bounded: a dispatch stuck inside a compiled collective whose
        # peer already quiesced would otherwise hang shutdown forever
        # (mirror stop_background's join timeout).
        if self._dispatch_lock.acquire(timeout=10.0):
            self._dispatch_lock.release()
        else:
            logger.warning(
                "quiesce: in-flight response dispatch did not finish "
                "within 10s; proceeding with backend teardown")
        self.tensor_queue.shutdown_flush()

    def detach(self):
        """Close the controller attachment and flush callbacks."""
        if hasattr(self.controller, "shutdown"):
            self.controller.shutdown()
        self.tensor_queue.shutdown_flush()

    def stop(self):
        self.stop_background()
        self.detach()

    # ------------------------------------------------------------------
    # the cycle loop
    # ------------------------------------------------------------------
    def _loop(self):
        while not self._shutdown.is_set():
            # Event-driven sleep: waking on submit keeps single-process
            # latency near zero; the timed wait bounds the negotiation
            # cadence like the reference cycle (default 1 ms).
            self._wake.wait(timeout=self._cycle_time_s)
            self._wake.clear()
            try:
                self._run_once()
            except Exception as e:  # surface to future submitters
                logger.exception("background runtime error")
                self._on_fatal(e)
                # A broken control plane never heals within a world
                # incarnation — stop cycling (elastic re-init builds
                # a fresh runtime) instead of re-raising every 1 ms.
                return

    def add_fatal_listener(self, fn):
        self._fatal_listeners.append(fn)

    def _on_fatal(self, err: Exception):
        if self._fatal_fired:
            return
        self._fatal_fired = True
        self._error = err
        if _fr.ENABLED:
            _fr.record(_fr.FATAL, rank=self.state.rank_info.rank,
                       role="runtime", error=str(err)[:200])
            _fr.trigger_dump("fatal")
        self.tensor_queue.shutdown_flush(err)
        for fn in list(self._fatal_listeners):
            try:
                fn(err)
            except Exception:
                logger.warning("fatal listener failed", exc_info=True)

    def replay_execute(self, resp: Response):
        """Execute a frozen-schedule response on the SUBMITTING thread
        (steady-state replay): same serialization and error contract as
        recv-thread direct dispatch — replay must never overlap a
        quiesce()'d backend teardown or another dispatch."""
        with self._dispatch_lock:
            if self._dispatch_disabled:
                return  # quiesced: entries already flushed with error
            try:
                self._perform_operation(resp)
            except Exception as e:
                logger.exception("replay dispatch error")
                self._on_fatal(e)

    def _dispatch_response(self, resp: Response):
        """Executes on the controller's recv thread (direct dispatch).
        Mirrors the background loop's error contract: a failure
        surfaces to future submitters and flushes outstanding
        callbacks."""
        with self._dispatch_lock:
            if self._dispatch_disabled:
                return  # quiesced: entries already flushed with error
            try:
                self._perform_operation(resp)
            except Exception as e:
                logger.exception("response dispatch error")
                self._on_fatal(e)

    def _run_once(self):
        if _fp.ENABLED:
            # Failpoint site: one background work cycle.  delay()
            # stretches the negotiation cadence; error() is fatal to
            # the incarnation (the _loop error contract).
            _fp.maybe_fail("runtime.cycle",
                           rank=self.state.rank_info.rank)
        _CYCLES.inc()
        if self.timeline:
            self.timeline.mark_cycle_start()
        t0 = time.perf_counter()
        pending = self.tensor_queue.pop_pending()
        _QUEUE_DEPTH.set(self.tensor_queue.outstanding())
        if not pending and self.state.rank_info.size == 1:
            return
        if self.timeline and pending:
            self.timeline.counter("queue_depth", {
                "pending": len(pending),
                "outstanding": self.tensor_queue.outstanding()})
        responses, leftovers = self.controller.compute_response_list(
            pending, self._entry_sizes,
            self.state.knobs.fusion_threshold_bytes)
        if leftovers:
            self.tensor_queue.push_back(leftovers)
        if self.stall_inspector is not None:
            # Local watchdog only: this rank's own stuck submissions
            # (e.g. unreachable coordinator).  Cross-rank attribution —
            # "ranks a,b submitted X, ranks c,d did not" — lives on the
            # rank-0 coordinator (controller_net.stall_report /
            # native coordinator), matching the reference's rank-0
            # stall inspector (stall_inspector.h:74-80).
            for req in pending:
                self.stall_inspector.record_uncached_tensor(
                    req.tensor_name, req.request_rank)
            self.stall_inspector.check()
        for resp in responses:
            self._perform_operation(resp)
        if pending or responses:
            cycle_dt = time.perf_counter() - t0
            _CYCLE_SECONDS.observe(cycle_dt)
            if _slo.ENABLED:
                # SLO cycle-time SLI (common/slo.py): O(1) append
                # under the tracker's leaf lock, evaluated cold at
                # ~1 Hz.  Disabled cost: this one attribute check.
                tr = _slo.tracker()
                if tr is not None:
                    tr.note_cycle(cycle_dt)

    # ------------------------------------------------------------------
    # execution (PerformOperation analog)
    # ------------------------------------------------------------------
    def _perform_operation(self, resp: Response):
        backend = self.state.backend
        my_rank = self.state.rank_info.rank
        if resp.process_set_ranks and my_rank not in resp.process_set_ranks:
            # A process-set collective this rank is not a member of: the
            # coordinator broadcasts to everyone, non-members simply
            # don't participate in the sub-mesh program.
            return
        _RESPONSES.inc(1, op=resp.response_type.name)
        entries: List[TensorTableEntry] = []
        for i, name in enumerate(resp.tensor_names):
            e = self.tensor_queue.pop_entry(name, resp.process_set_id)
            if e is None and self._joined and resp.response_type in (
                    ResponseType.ALLREDUCE, ResponseType.ADASUM,
                    ResponseType.ALLGATHER, ResponseType.BROADCAST,
                    ResponseType.REDUCESCATTER):
                # Joined rank: substitute a zero tensor so the compiled
                # collective still has all participants.
                import numpy as np
                from .message import np_dtype
                shape = tuple(resp.tensor_shapes[i]) \
                    if i < len(resp.tensor_shapes) else ()
                if resp.response_type == ResponseType.ALLGATHER:
                    shape = (0,) + shape[1:]
                zero = np.zeros(shape, dtype=np_dtype(resp.tensor_type))
                e = TensorTableEntry(tensor_name=name, tensor=zero,
                                     callback=lambda ok, r: None,
                                     process_set_id=resp.process_set_id)
                _JOIN_ZEROS.inc()
            if e is not None:
                entries.append(e)
            if self.stall_inspector is not None:
                self.stall_inspector.remove(name)
            if self.timeline:
                self.timeline.negotiate_end(name)

        if resp.response_type == ResponseType.ERROR:
            err = RuntimeError(resp.error_message)
            for e in entries:
                e.callback(False, err)
            return
        if resp.response_type == ResponseType.JOIN:
            for e in entries:
                e.callback(True, resp.last_joined_rank)
            return
        if resp.response_type == ResponseType.BARRIER:
            for e in entries:
                e.callback(True, None)
            return
        if not entries:
            return

        names = [e.tensor_name for e in entries]
        tl_name = names[0]
        ps_ranks = tuple(resp.process_set_ranks)
        sg_t0 = time.perf_counter() if _sg.ENABLED else 0.0
        if self.timeline:
            self.timeline.counter("fused_bytes", {"bytes": int(sum(
                getattr(e.tensor, "nbytes", 0) for e in entries))})
        try:
            if self.timeline:
                self.timeline.start_activity(
                    tl_name, f"XLA_{resp.response_type.name}")
            if resp.response_type in (ResponseType.ALLREDUCE,):
                arrays = [e.tensor for e in entries]
                results = backend.allreduce(
                    arrays, resp.reduce_op, resp.prescale_factor,
                    resp.postscale_factor, ps_ranks)
            elif resp.response_type == ResponseType.ADASUM:
                arrays = [e.tensor for e in entries]
                results = backend.adasum_allreduce(
                    arrays, resp.prescale_factor, resp.postscale_factor,
                    ps_ranks)
            elif resp.response_type == ResponseType.ALLGATHER:
                results = backend.allgather(
                    [e.tensor for e in entries], resp.tensor_sizes,
                    ps_ranks)
            elif resp.response_type == ResponseType.BROADCAST:
                results = backend.broadcast(
                    [e.tensor for e in entries], resp.root_rank,
                    ps_ranks)
            elif resp.response_type == ResponseType.ALLTOALL:
                # tensor_sizes carries the coordinator-assembled
                # group×group send-split matrix (one alltoall per
                # response — the type is never fused), so the backend
                # skips its own split-exchange collective.
                results = []
                matrix = resp.tensor_sizes or None
                for e in entries:
                    out, recv_splits = backend.alltoall(
                        e.tensor, e.splits, ps_ranks,
                        split_matrix=matrix)
                    results.append((out, recv_splits))
            elif resp.response_type == ResponseType.REDUCESCATTER:
                results = backend.reducescatter(
                    [e.tensor for e in entries], resp.reduce_op,
                    ps_ranks)
            else:
                raise RuntimeError(
                    f"Unknown response type {resp.response_type}")
            if self.timeline:
                self.timeline.end_activity(tl_name)
        except Exception as err:
            if self.timeline:
                self.timeline.end_activity(tl_name)
            for e in entries:
                e.callback(False, err)
            return

        if _sg.ENABLED:
            # The fused→executed phase slice (the e2e EWMA comes from
            # the latency wrapper above); per-rank publication happens
            # on the cold MR-reply path, never here.
            self.phase_collector.note_exec(
                time.perf_counter() - sg_t0)
        if _slo.ENABLED:
            # SLO throughput SLI: one fused response completes
            # len(entries) collective ops.  Disabled cost: this one
            # attribute check.
            tr = _slo.tracker()
            if tr is not None:
                tr.note_op(len(entries))
        for e, result in zip(entries, results):
            e.callback(True, result)
