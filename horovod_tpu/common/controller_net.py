"""Multi-process controller: coordinator/worker negotiation over TCP.

The TPU port of the reference's coordinator protocol (reference:
controller.h:69-102 protocol spec; mpi_controller.cc / gloo_controller.cc
transport implementations): every rank pushes its ready Requests to the
rank-0 coordinator; the coordinator counts readiness per tensor
(IncrementTensorCount), validates and constructs fused Responses, and
broadcasts one ordered ResponseList to every rank.  Each rank then
executes the identical fused batch — which on the XLA data plane means
every process enters the same compiled collective program (order
determinism is what makes the executable cache effective, SURVEY §7).

Deltas from the reference:
  * event-driven push instead of a 1 ms gather cycle — ranks send only
    when they have pending work, the coordinator fires a response batch
    as soon as every rank has reported a tensor (lower latency than
    cycle polling, no idle chatter over DCN);
  * transport is plain length-prefixed TCP (no MPI/gloo dependency) —
    the launcher provides HOROVOD_CONTROLLER_ADDR.
"""
# hvdlint-module: hot-path (instrumentation must hide behind one attribute check — docs/static_analysis.md)

import json
import logging
import os
import queue
import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from . import env as env_mod
from . import failpoints as _fp
from . import flight_recorder as _fr
from . import metrics
from . import profiler as _prof
from . import relay as relay_mod
from . import slo as _slo
from . import straggler as _sg
from .controller import Controller, MessageTable, construct_response
from .fusion import fuse_responses
from .message import (Request, RequestType, Response, ResponseType,
                      dtype_size, pack_bit_batches, pack_bits,
                      pack_request_list, pack_response_list,
                      unpack_bit_batches, unpack_bits,
                      unpack_request_list, unpack_response_list)
from .response_cache import (CACHEABLE, CoordinatorCache,
                             WorkerResponseCache, merge_responses,
                             request_signature, signature_to_request,
                             split_response)

logger = logging.getLogger("horovod_tpu.controller_net")

CONTROLLER_ADDR_ENV = "HOROVOD_CONTROLLER_ADDR"

_MAGIC_REQ = b"RQ"      # worker→coord: full request list
_MAGIC_RESP = b"RS"     # coord→worker: full response list
_MAGIC_HITS = b"CH"     # worker→coord: cache-hit bit list (fast path)
_MAGIC_CACHE = b"CB"    # coord→worker: fused batches of cache bits
_MAGIC_EVICT = b"EV"    # coord→worker: evicted cache bits
_MAGIC_PARAMS = b"PA"   # coord→worker: autotuned runtime parameters
_MAGIC_ABORT = b"AB"    # coord→worker: membership broken, fail fast
_MAGIC_METRICS_REQ = b"MQ"  # coord→worker: send a metrics snapshot
_MAGIC_METRICS_REP = b"MR"  # worker→coord: metrics snapshot (JSON)
_MAGIC_HB = b"HB"       # both ways: liveness heartbeat (empty payload)
_MAGIC_WELCOME = b"WE"  # coord→worker: reconnect handshake answer

# Per-link replay buffers for the reconnecting control channel: each
# side keeps its last N stream frames so a link that drops and resumes
# inside the grace window replays exactly the frames the peer missed
# (TCP ordering makes the frame ordinal an implicit sequence number —
# no wire-format change).  A resume point older than the buffer is
# unrecoverable and promotes the rank to lost.
_LINK_LOG_FRAMES = 512

# Out-of-stream frames: pure signals (HB liveness) and absolute
# snapshots (MQ polls / MR replies) are excluded from the replay
# rings and the stream cursors on BOTH sides — replaying them buys
# nothing, and excluding them is what lets a relay consume a child's
# HBs (one relay HB stands in for the subtree) and aggregate its MR
# replies into one MA frame without desyncing the resume arithmetic.
# Frame bytes on the wire are unchanged; only the cursor accounting
# moved, symmetrically, on both endpoints.
_OOS_DOWN = (_MAGIC_HB, _MAGIC_METRICS_REQ)
_OOS_UP = (_MAGIC_HB, _MAGIC_METRICS_REP)


class _LinkToken:
    """Mux registration for one root link in tree mode: a direct leaf
    (kind="leaf", ident=rank, gen=conn generation) or a relay link
    (kind="relay", ident=relay id, gen=relay generation)."""
    __slots__ = ("kind", "ident", "gen", "clean")

    def __init__(self, kind, ident, gen):
        self.kind = kind
        self.ident = ident
        self.gen = gen
        self.clean = False

    def __repr__(self):
        return "<link %s %s g%d>" % (self.kind, self.ident, self.gen)

_FRAMES_SENT = metrics.counter(
    "hvd_frames_sent_total", "Control-plane frames sent, by kind")
_FRAMES_RECV = metrics.counter(
    "hvd_frames_recv_total", "Control-plane frames received, by kind")
_BYTES_SENT = metrics.counter(
    "hvd_bytes_sent_total", "Control-plane bytes sent (incl. headers)")
_BYTES_RECV = metrics.counter(
    "hvd_bytes_recv_total",
    "Control-plane bytes received (incl. headers)")
_INLINE = metrics.counter(
    "hvd_inline_cache_total",
    "Submitting-thread inline fast-path outcomes (hit = CH frame sent "
    "without waking the background thread)")
_ROUNDS = metrics.counter(
    "hvd_negotiation_rounds_total",
    "Coordinator broadcast rounds, by kind (fast = pure cache-bit CB "
    "frame, full = negotiated RS frame)")
_COORD_TENSORS = metrics.counter(
    "hvd_negotiated_tensors_total",
    "Tensors completed on the coordinator, by path")
_UPLINK_BATCH = metrics.histogram(
    "hvd_uplink_requests_per_frame",
    "Requests/bits coalesced into one uplink frame, by kind (drain-"
    "all-pending coalescing: frame count tracks batch count, not "
    "tensor count)", bounds=metrics.COUNT_BUCKETS)
_HEARTBEATS = metrics.counter(
    "hvd_liveness_heartbeats_total",
    "HB liveness frames sent, by role (suppressed while real traffic "
    "flows, so steady-state training sends none)")
_LIVENESS_TIMEOUTS = metrics.counter(
    "hvd_liveness_timeouts_total",
    "Peers promoted to dead by the liveness machinery, by role and "
    "kind (coordinator: silent rank; worker: silent coordinator)")
_RECONNECTS = metrics.counter(
    "hvd_reconnects_total",
    "Control-channel reconnect outcomes (resumed = session replayed "
    "transparently; failed = worker gave up; refused = coordinator "
    "could not replay; expired = coordinator grace window ran out)")


# The wire-framing primitives live ONCE, in relay.py (this module
# imports relay; the reverse would be a cycle).  The old private names
# stay as aliases — tests and tools import them from here.
_send_frame = relay_mod.send_frame
_recv_frame = relay_mod.recv_frame


class _LinkSilent(Exception):
    """Raised by a bounded recv's idle callback: the peer has been
    silent past the liveness deadline (the link may still be open —
    SIGSTOP, GIL deadlock, half-open socket)."""


def _recv_exact_bounded(sock: socket.socket, n: int, on_idle,
                        on_data=None):
    """`_recv_exact` for a socket with a poll timeout set: every
    timeout expiry calls ``on_idle()`` — which raises to abort the
    wait — so no control-plane recv can block forever.  ``on_data``
    fires on every received chunk so a large frame trickling in slower
    than the liveness timeout still counts as a live peer."""
    buf = b""
    while len(buf) < n:
        try:
            # hvdlint: bounded-by(caller arms a poll settimeout; every
            # expiry raises through on_idle)
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            on_idle()
            continue
        if not chunk:
            return None
        if on_data is not None:
            on_data()
        buf += chunk
    return buf


def _recv_frame_bounded(sock: socket.socket, on_idle, on_data=None
                        ) -> Optional[Tuple[bytes, bytes]]:
    head = _recv_exact_bounded(sock, 6, on_idle, on_data)
    if head is None:
        return None
    magic, ln = head[:2], struct.unpack("<I", head[2:])[0]
    payload = _recv_exact_bounded(sock, ln, on_idle, on_data)
    if payload is None:
        return None
    return magic, payload


def _parse_registration(payload: bytes) -> Tuple[int, dict]:
    """Registration frame payload: 4-byte rank, optionally followed by
    a JSON session blob (reconnecting-channel handshake).  The plain
    4-byte form remains valid — and is all the native coordinator ever
    sees (it reads the first 4 bytes and ignores the rest).  A
    too-short payload (garbage client) parses as an invalid rank
    rather than raising into the accept loop."""
    if len(payload) < 4:
        return -1, {}
    rank = struct.unpack("<i", payload[:4])[0]
    session = {}
    if len(payload) > 4:
        try:
            session = json.loads(payload[4:].decode())
        except (ValueError, UnicodeDecodeError):
            session = {}
    return rank, session


class CoordinatorServer:
    """Rank-0 service: accepts one connection per rank (including a
    loopback connection from rank 0's own worker), matches requests,
    broadcasts fused response lists."""

    def __init__(self, size: int, bind_addr: str = "0.0.0.0",
                 port: int = 0, fusion_threshold: int = 64 << 20,
                 timeline=None, elastic: bool = False,
                 allow_ephemeral_fallback: bool = False,
                 param_manager=None, cache_capacity: int = 1024,
                 stall_warning_time_s: float = 60.0,
                 stall_shutdown_time_s: float = 0.0,
                 metrics_interval_s: float = 0.0,
                 liveness_interval_s: float = 0.0,
                 liveness_timeout_s: float = 0.0,
                 reconnect_grace_s: float = 0.0,
                 registration_timeout_s: float = 30.0,
                 fanout: int = 0,
                 on_rank_lost=None,
                 tune_session=None,
                 on_rank_slow=None):
        self.size = size
        self.fusion_threshold = fusion_threshold
        self.timeline = timeline
        self.elastic = elastic
        self.allow_ephemeral_fallback = allow_ephemeral_fallback
        self._broken = False
        # Autotuner (rank-0 only: fusion planning happens here, so the
        # threshold needs no cross-rank sync — reference
        # parameter_manager.cc semantics, SURVEY §2.1).
        self.param_manager = param_manager
        if param_manager is not None:
            param_manager.fusion_threshold_bytes = fusion_threshold
        # Last PA-frame-synced categorical params version (-1 = stock
        # configuration, nothing announced yet).
        self._synced_params_version = -1
        self._synced_params = None
        # Autotune-then-freeze session (horovod_tpu/tune): scores
        # every round per cycle-class, proposes knobs, freezes.  Its
        # announcements ride the same PA frame + registration-replay
        # machinery as the legacy param_manager; its per-class fusion
        # thresholds are applied at fuse time below.  Priming
        # _synced_params here makes the startup announcement (search
        # active / profile-frozen) reach every rank at registration.
        self.tune_session = tune_session
        if tune_session is not None:
            p = tune_session.take_announcement()
            if p is not None:
                self._synced_params = json.dumps(p).encode()
        self._table = MessageTable()
        self._seen = 0
        self._departed = 0
        self._departed_cond = threading.Condition()
        # (psid, name) -> element count, for fusion byte accounting
        self._elem_cache: Dict[tuple, int] = {}
        # (psid, name) -> grouped-submission id (group-atomic fusion)
        self._group_ids: Dict[tuple, int] = {}
        self._joined: Set[int] = set()
        self._last_joined = -1
        # barrier (psid, name) -> ranks arrived
        self._barriers: Dict[tuple, Set[int]] = {}
        # barrier (psid, name) -> member ranks (for stall attribution)
        self._barrier_members: Dict[tuple, Tuple[int, ...]] = {}
        # --- response-cache fast path (reference controller.cc:81-236) ---
        self._cache = CoordinatorCache(cache_capacity)
        # (psid, name) -> True while every contribution this round came
        # from a live cache bit (a full request degrades the round)
        self._bit_only: Dict[tuple, bool] = {}
        self._pending_evictions: List[int] = []
        self.stats = {"full_rounds": 0, "fast_rounds": 0,
                      "fast_tensors": 0, "negotiated_tensors": 0}
        # --- coordinator-side stall attribution (reference
        #     stall_inspector.h:74-80: rank 0 names which ranks are
        #     missing a tensor) ---
        self._first_seen: Dict[tuple, float] = {}
        self._stall_warning_s = stall_warning_time_s
        self._stall_shutdown_s = stall_shutdown_time_s
        self._stall_logged: Dict[tuple, float] = {}
        self._conns: Dict[int, socket.socket] = {}
        # Formation gate: NOTHING may be negotiated (and so no frame
        # broadcast) until every rank of this incarnation has
        # connected — a response completed among early connectors
        # would never reach a late one (measured: subgroup-first
        # traffic wedged/desynced ranks that missed the first RS,
        # tests/test_stress_protocol.py).  Uplink frames arriving
        # before formation buffer here and drain, in arrival order,
        # when the last rank registers.
        self._formed = size <= 1
        self._pre_formed: List[tuple] = []  # (kind, rank, payload)
        self._started_at = time.monotonic()  # formation-stall clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # --- self-healing control plane (docs/failure_recovery.md) ---
        # Liveness: bounded-time detection of wedged-but-connected
        # ranks via HB heartbeats + a sweep, with no dependence on a
        # collective being in flight.  Reconnect: a dead socket parks
        # the rank in limbo for a grace window; a resume replays the
        # frames it missed from the per-rank out-log.
        self.liveness_interval_s = liveness_interval_s
        self.liveness_timeout_s = liveness_timeout_s or \
            2.0 * liveness_interval_s
        self.reconnect_grace_s = reconnect_grace_s
        self.registration_timeout_s = registration_timeout_s
        self._on_rank_lost_hook = on_rank_lost
        self._last_heard: Dict[int, float] = {}
        self._departure_counted: Set[int] = set()
        # Per-rank stream lock: frame processing + the _in_count
        # cursor advance are atomic under it, and the resume handshake
        # takes it to wait out an in-flight frame — so a frame is
        # either fully processed (counted, not replayed) or discarded
        # un-counted (replayed by the worker).  Never both.
        self._stream_locks: Dict[int, threading.Lock] = {}
        self._sessions: Dict[int, str] = {}
        self._conn_gen: Dict[int, int] = {}   # supersession guard
        self._limbo: Dict[int, float] = {}    # rank -> disconnect time
        self._lost: Set[int] = set()          # final (idempotence)
        self._out_log: Dict[int, deque] = {}  # rank -> (ord, magic, pl)
        self._out_seq: Dict[int, int] = {}    # downlink frames sent
        self._in_count: Dict[int, int] = {}   # uplink frames processed
        self._last_broadcast_t = time.monotonic()
        # --- relay-tree fan-out (common/relay.py, HOROVOD_COORD_FANOUT)
        # Per-rank stream state above stays HERE even for ranks served
        # through a relay: relays are stateless forwarders, so every
        # re-home resumes against the root's out-logs and cursors.
        self._plan = relay_mod.plan_tree(size, fanout) \
            if fanout > 0 else None
        self._tree = self._plan is not None
        self._rank_via: Dict[int, int] = {}    # rank -> root-side relay
        self._via_epoch: Dict[int, int] = {}   # rank -> child-conn epoch
        self._via_suspect: Dict[int, tuple] = {}  # rank -> (t, gen)
        self._relay_conns: Dict[int, socket.socket] = {}
        self._relay_gen: Dict[int, int] = {}
        self._relay_depth: Dict[int, int] = {}
        self._relay_metrics: Dict[int, dict] = {}
        # Lazy deadline heap: the liveness sweep visits only links
        # whose deadline lapsed, O(due) per tick instead of O(world)
        # (relay.DeadlineHeap; pinned by tests/test_relay_tree.py).
        self._lheap = relay_mod.DeadlineHeap()
        # Plain-int probe counters (tools/chaos_soak scale probe reads
        # them; ints, not registry metrics, so the hot path pays only
        # the increments).
        self.uplink_frames = 0
        self.bcast_ns = 0
        self.bcast_sends = 0
        # --- live straggler observatory (common/straggler.py): fold
        #     the CH/RQ arrival order — today's discard — into
        #     per-rank lag EWMAs, adopt the MR/MA-carried worker phase
        #     summaries so attribution keeps working during replay,
        #     and refresh the hvd_straggler_score gauges on a small
        #     loop.  None when disarmed: the frame dispatch hot path
        #     then pays exactly one attribute check.  Constructed
        #     BEFORE any serving thread starts (frames may dispatch
        #     the moment the accept loop runs).
        self._straggler = _sg.StragglerScorer(
            size, on_slow=on_rank_slow) if _sg.ENABLED else None
        self._straggler_thread = None
        self._mux = None
        if self._tree:
            # Selector/batched recv loop: ONE thread drains every root
            # link (O(fanout) relay links + direct leaves) instead of
            # a thread per rank.  Flat star (fanout=0) keeps the
            # thread-per-link path byte-identically.
            self._mux = relay_mod.FrameMux(
                self._mux_frame, self._mux_close,
                name="hvd-coord-mux", on_data=self._mux_data)
            self._mux.start()
            logger.info("relay-tree coordinator: %s",
                        self._plan.to_meta())
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._srv.bind((bind_addr, port))
        except OSError:
            if not self.allow_ephemeral_fallback:
                # Without a rendezvous store to publish the real port,
                # an ephemeral fallback would leave workers hanging on
                # the dead env-contract port — fail crisply instead.
                raise
            # The launcher-chosen port got taken in the meantime; fall
            # back to an ephemeral port.  The actual address is
            # published through the rendezvous KV store, which workers
            # prefer over the env contract.
            logger.warning("controller port %d unavailable; using an "
                           "ephemeral port", port)
            self._srv.bind((bind_addr, 0))
        self._srv.listen(size + 4)
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="hvd-coord-accept", daemon=True)
        self._threads: List[threading.Thread] = []
        self._accept_thread.start()
        self._stall_thread = None
        if stall_warning_time_s > 0:
            self._stall_thread = threading.Thread(
                target=self._stall_loop, name="hvd-coord-stall",
                daemon=True)
            self._stall_thread.start()
        # The sweep must also run for grace-only configurations
        # (liveness off, reconnects on): limbo expiry lives in the
        # sweep, and without it a permanently dead rank would park in
        # limbo forever.
        self._liveness_thread = None
        if liveness_interval_s > 0 or reconnect_grace_s > 0:
            self._liveness_thread = threading.Thread(
                target=self._liveness_loop, name="hvd-coord-liveness",
                daemon=True)
            self._liveness_thread.start()
        # --- cross-rank metrics aggregation (MQ/MR frames): collect
        #     per-rank registry snapshots and expose the merged view,
        #     the metrics analog of the rank-0 stall report ---
        self._rank_metrics: Dict[int, dict] = {}
        self._metrics_interval_s = metrics_interval_s
        self._metrics_thread = None
        if metrics_interval_s > 0:
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="hvd-coord-metrics",
                daemon=True)
            self._metrics_thread.start()
        if self._straggler is not None:
            self._straggler_thread = threading.Thread(
                target=self._straggler_loop,
                name="hvd-coord-straggler", daemon=True)
            self._straggler_thread.start()

    def _accept_loop(self):
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # First frame identifies the rank.  Bound the wait so a
            # connected-but-silent client can't stall registration of
            # the remaining ranks (HOROVOD_REGISTRATION_TIMEOUT).
            conn.settimeout(self.registration_timeout_s)
            try:
                frame = _recv_frame(conn)
            except (socket.timeout, OSError):
                conn.close()
                continue
            if frame is None:
                conn.close()
                continue
            if frame[0] != _MAGIC_REQ:
                # frame-parity: the only first frame a link may send
                # is an RQ registration.  Anything else is a garbage /
                # misdirected client — drop the connection, never
                # guess a rank out of arbitrary bytes.
                logger.warning("refusing connection whose first frame "
                               "is %r (want RQ registration)",
                               frame[0])
                conn.close()
                continue
            rank, sess = _parse_registration(frame[1])
            if relay_mod.is_relay_reg(rank):
                self._register_relay(
                    relay_mod.relay_id_from_reg(rank), sess, conn)
            elif rank < 0 or rank >= self.size:
                logger.warning("refusing registration with invalid "
                               "rank %d", rank)
                try:
                    conn.close()
                except OSError:
                    pass
            elif sess.get("resume"):
                self._try_resume(rank, sess, conn)
            else:
                self._register_fresh(rank, sess, conn)

    def _register_relay(self, rid: int, sess: dict,
                        conn: socket.socket):
        """A relay link attached (tree mode): it serves every leaf
        whose RG registration it forwards; it carries no stream state
        of its own (stateless fail-stop forwarder)."""
        if not self._tree:
            logger.warning("refusing relay %d registration: "
                           "HOROVOD_COORD_FANOUT is off", rid)
            try:
                conn.close()
            except OSError:
                pass
            return
        with self._lock:
            old = self._relay_conns.get(rid)
            self._relay_conns[rid] = conn
            self._relay_gen[rid] = gen = self._relay_gen.get(rid, 0) + 1
            self._relay_depth[rid] = max(1, int(sess.get(
                "depth_below", 1)))
            key = ("relay", rid)
            self._last_heard[key] = time.monotonic()
            if self.liveness_interval_s > 0:
                self._lheap.schedule(
                    key, self._last_heard[key] +
                    env_mod.depth_aware_liveness_timeout(
                        self.liveness_timeout_s,
                        self._relay_depth[rid]))
        if old is not None and old is not conn:
            try:
                old.close()
            except OSError:
                pass
        # hvdlint: bounded-by(mux-served link: the selector loop polls
        # at 0.2s and liveness sweeps cover silent relays)
        conn.settimeout(None)
        logger.info("relay %d link registered (depth_below=%d)", rid,
                    self._relay_depth[rid])
        if _fr.ENABLED:
            _fr.record(_fr.RELAY_ATTACH, rank=0, role="coord",
                       relay=rid, depth=self._relay_depth[rid],
                       cyc=gen)
        self._mux.add(_LinkToken("relay", rid, gen), conn)

    def _install_conn_locked(self, rank: int, conn: socket.socket) -> int:
        """Install ``conn`` as rank's live link (superseding any stale
        one) and return its link generation — rank-loop exits compare
        generations so a replaced link's death can't demote a resumed
        rank (caller holds self._lock)."""
        old = self._conns.get(rank)
        if old is not None and old is not conn:
            try:
                old.close()
            except OSError:
                pass
        self._conns[rank] = conn
        # A direct link supersedes any relay attachment (re-home to
        # the root after a relay loss).
        self._rank_via.pop(rank, None)
        self._via_epoch.pop(rank, None)
        self._via_suspect.pop(rank, None)
        self._conn_gen[rank] = self._conn_gen.get(rank, 0) + 1
        self._stream_locks.setdefault(rank, threading.Lock())
        self._last_heard[rank] = time.monotonic()
        if self.liveness_interval_s > 0:
            self._lheap.schedule(rank, self._last_heard[rank] +
                                 self.liveness_timeout_s)
        if self._tree:
            # Mux-served link: select() gates recv, no poll timeout.
            # hvdlint: bounded-by(selector loop polls at 0.2s)
            conn.settimeout(None)
        elif self.liveness_interval_s > 0:
            # Bounded registered-link recv: the rank loop polls at a
            # fraction of the liveness timeout instead of blocking in
            # recv forever (the pre-liveness settimeout(None) hole).
            conn.settimeout(self._sweep_period())
        else:
            # hvdlint: bounded-by(liveness off is the documented
            # legacy opt-out: the stall inspector is the only clock;
            # HOROVOD_LIVENESS_INTERVAL>0 bounds this link)
            conn.settimeout(None)
        return self._conn_gen[rank]

    def _register_fresh(self, rank: int, sess: dict,
                        conn: socket.socket):
        if _fr.ENABLED:
            _fr.record(_fr.REGISTER, rank=0, role="coord", peer=rank,
                       sess=(sess.get("session") or "")[:8])
        with self._lock:
            gen = self._install_conn_locked(rank, conn)
            self._sessions[rank] = sess.get("session", "")
            self._limbo.pop(rank, None)
            # A fresh session starts a fresh frame stream.
            self._out_seq[rank] = 0
            self._in_count[rank] = 0
            if self.reconnect_grace_s > 0:
                self._out_log[rank] = deque(maxlen=_LINK_LOG_FRAMES)
            # Late joiners (elastic re-rendezvous) must start from
            # the currently announced parameters, and they see the
            # PA frame before any response frame — the same stream
            # position every other worker saw it at.
            if self._synced_params is not None:
                self._send_to_rank_locked(rank, _MAGIC_PARAMS,
                                          self._synced_params)
            self._maybe_form_locked()
        self._note_fresh_life(rank)
        self._serve_link(rank, conn, gen)

    def _attached_ranks_locked(self) -> Set[int]:
        """Leaf ranks currently attached — directly or via a relay
        (caller holds self._lock)."""
        ranks = set(self._conns.keys())
        ranks.update(self._rank_via.keys())
        return ranks

    def _maybe_form_locked(self):
        if not self._formed and \
                len(self._attached_ranks_locked()) >= self.size:
            self._formed = True
            pre, self._pre_formed = self._pre_formed, []
            for kind, r, payload in pre:
                self._dispatch_uplink_locked(kind, r, payload)

    def _note_fresh_life(self, rank: int):
        with self._departed_cond:
            # A fresh session is a new rank life: it gets its own
            # seen/departed pair (a restarted process re-registering
            # mid-incarnation must keep the drain arithmetic balanced).
            self._departure_counted.discard(rank)
            self._seen += 1
            self._departed_cond.notify_all()

    def _serve_link(self, rank: int, conn: socket.socket, gen: int):
        if self._tree:
            self._mux.add(_LinkToken("leaf", rank, gen), conn)
        else:
            self._spawn_rank_loop(rank, conn, gen)

    # ------------------------------------------------------------------
    # tree mode: the selector/batched recv loop (one thread, all links)
    # ------------------------------------------------------------------
    def _mux_data(self, token: "_LinkToken"):
        # Chunk-level liveness refresh: a large frame trickling in
        # slower than the deadline still counts as a live peer (the
        # thread-mode on_data analog).
        key = token.ident if token.kind == "leaf" \
            else ("relay", token.ident)
        self._last_heard[key] = time.monotonic()

    def _mux_frame(self, token: "_LinkToken", magic: bytes,
                   payload: bytes):
        if self._stop.is_set():
            return False
        if token.kind == "relay":
            return self._relay_frame(token, magic, payload)
        return self._direct_frame(token, magic, payload)

    def _direct_frame(self, token: "_LinkToken", magic: bytes,
                      payload: bytes):
        """One frame from a DIRECT leaf link in tree mode — the exact
        semantics of the flat-star rank loop body."""
        rank, gen = token.ident, token.gen
        if self._conn_gen.get(rank, 0) != gen:
            return False  # superseded; on_close is a no-op via gen
        self._last_heard[rank] = time.monotonic()
        if magic in _OOS_UP:
            _FRAMES_RECV.inc(1, kind=magic.decode("ascii", "replace"))
            if _fr.ENABLED and magic == _MAGIC_HB:
                _fr.record(_fr.HB_RX, rank=0, role="coord", peer=rank)
            if magic == _MAGIC_METRICS_REP:
                self._handle_metrics_snapshot(rank, payload)
            return True
        self.uplink_frames += 1
        if _fr.ENABLED:
            _fr.record(_fr.FRAME_RX, rank=0, role="coord", peer=rank,
                       frame=magic.decode("ascii", "replace"),
                       nbytes=len(payload),
                       seq=self._in_count.get(rank, 0) + 1, cyc=gen)
        if _fp.ENABLED:
            try:
                if _fp.maybe_fail("coord.frame_recv",
                                  rank=rank) == "drop":
                    lock = self._stream_locks.get(rank)
                    if lock is not None:
                        with lock:
                            if self._conn_gen.get(rank, 0) == gen:
                                self._in_count[rank] = \
                                    self._in_count.get(rank, 0) + 1
                    return True
            except _fp.FailpointError:
                return False  # injected error kills this link
        _FRAMES_RECV.inc(1, kind=magic.decode("ascii", "replace"))
        _BYTES_RECV.inc(len(payload) + 6)
        stream_lock = self._stream_locks.get(rank)
        if stream_lock is None:
            return False
        with stream_lock:
            if self._conn_gen.get(rank, 0) != gen:
                return False
            try:
                if magic == _MAGIC_HITS:
                    self._handle_cache_hits(rank, unpack_bits(payload))
                    return True
                requests, shutdown = unpack_request_list(payload)
                if shutdown:
                    token.clean = True
                    return False
                self._handle_requests(rank, requests)
                return True
            finally:
                self._in_count[rank] = self._in_count.get(rank, 0) + 1

    def _relay_frame(self, token: "_LinkToken", magic: bytes,
                     payload: bytes):
        rid, gen = token.ident, token.gen
        if self._relay_gen.get(rid, 0) != gen:
            return False
        self._last_heard[("relay", rid)] = time.monotonic()
        if magic == _MAGIC_HB:
            _FRAMES_RECV.inc(1, kind="HB")
            if _fr.ENABLED:
                _fr.record(_fr.HB_RX, rank=0, role="coord", relay=rid)
            return True
        if magic == relay_mod.MAGIC_METRICS_AGG:
            self._handle_metrics_aggregate(rid, payload)
            return True
        if magic == relay_mod.MAGIC_RELAY_LOST:
            self._handle_relay_lost(rid, payload)
            return True
        if magic == relay_mod.MAGIC_RELAY_BATCH:
            self.uplink_frames += 1
            _FRAMES_RECV.inc(1, kind="RB")
            _BYTES_RECV.inc(len(payload) + 6)
            try:
                items = relay_mod.unpack_rb_items(payload)
            except (struct.error, IndexError):
                logger.error("corrupt RB frame from relay %d; "
                             "dropping the link", rid)
                return False
            for origin, epoch, imagic, ipayload in items:
                self._relay_item(rid, origin, epoch, imagic, ipayload)
            return True
        logger.warning("unexpected %s frame on relay link %d",
                       magic.decode("ascii", "replace"), rid)
        return True

    def _relay_item(self, rid: int, origin: int, epoch: int,
                    magic: bytes, payload: bytes):
        """One leaf uplink item forwarded through a relay.  Stream
        items (CH/RQ) are processed under the leaf's stream lock with
        an attachment check — (relay id, child epoch) must match the
        rank's current attachment, so frames in flight from a
        superseded child socket are discarded UN-counted and the
        leaf's resume replay re-delivers them exactly once."""
        if magic == relay_mod.MAGIC_REGISTER:
            rank, sess = _parse_registration(payload)
            if rank != origin:
                logger.warning("relay %d forwarded a registration for "
                               "rank %d tagged origin %d; ignoring",
                               rid, rank, origin)
                return
            if sess.get("resume"):
                self._try_resume_remote(rank, sess, rid, epoch)
            else:
                self._register_fresh_remote(rank, sess, rid, epoch)
            return
        if magic in _OOS_UP:
            # Relays normally consume HB/MR; handle stragglers anyway.
            if magic == _MAGIC_METRICS_REP:
                self._handle_metrics_snapshot(origin, payload)
            return
        if _fr.ENABLED:
            _fr.record(_fr.FRAME_RX, rank=0, role="coord",
                       peer=origin, via=rid,
                       frame=magic.decode("ascii", "replace"),
                       nbytes=len(payload),
                       seq=self._in_count.get(origin, 0) + 1,
                       cyc=epoch)
        if _fp.ENABLED:
            try:
                if _fp.maybe_fail("coord.frame_recv",
                                  rank=origin) == "drop":
                    lock = self._stream_locks.get(origin)
                    if lock is not None:
                        with lock:
                            if self._rank_via.get(origin) == rid and \
                                    self._via_epoch.get(origin) == epoch:
                                self._in_count[origin] = \
                                    self._in_count.get(origin, 0) + 1
                    return
            except _fp.FailpointError:
                logger.warning("failpoint coord.frame_recv: injected "
                               "error on relayed frame; dropping it")
                return
        stream_lock = self._stream_locks.get(origin)
        if stream_lock is None:
            return  # never registered; nothing to do
        with stream_lock:
            if self._rank_via.get(origin) != rid or \
                    self._via_epoch.get(origin) != epoch:
                return  # superseded attachment; un-counted
            try:
                if magic == _MAGIC_HITS:
                    self._handle_cache_hits(origin,
                                            unpack_bits(payload))
                    return
                requests, shutdown = unpack_request_list(payload)
                if shutdown:
                    self._remote_clean_departure(origin)
                    return
                self._handle_requests(origin, requests)
            finally:
                self._in_count[origin] = \
                    self._in_count.get(origin, 0) + 1

    def _remote_clean_departure(self, rank: int):
        """Shutdown frame from a relay-attached rank — the mirror of
        the rank loop's clean exit (caller holds the stream lock; the
        server lock nests inside it everywhere)."""
        with self._lock:
            self._detach_rank_locked(rank)
        self._count_departed(rank)
        if not self._stop.is_set():
            self._promote_lost(rank, clean=True)

    def _detach_rank_locked(self, rank: int):
        old = self._conns.pop(rank, None)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._rank_via.pop(rank, None)
        self._via_epoch.pop(rank, None)
        self._via_suspect.pop(rank, None)

    def _register_fresh_remote(self, rank: int, sess: dict, rid: int,
                               epoch: int):
        """Fresh leaf registration forwarded through a relay: the
        mirror of _register_fresh with the relay link as transport.
        The targeted WE ack opens the relay's broadcast gate for this
        child — broadcasts the root sent before this point were never
        logged for the rank, so the relay must not deliver them."""
        with self._lock:
            if self._relay_conns.get(rid) is None:
                return
            self._detach_rank_locked(rank)
            self._conn_gen[rank] = self._conn_gen.get(rank, 0) + 1
            self._rank_via[rank] = rid
            self._via_epoch[rank] = epoch
            self._stream_locks.setdefault(rank, threading.Lock())
            self._last_heard[rank] = time.monotonic()
            self._sessions[rank] = sess.get("session", "")
            self._limbo.pop(rank, None)
            # Relay-attached ranks report metrics through their
            # relay's MA aggregate; a frozen direct snapshot left
            # behind would double count them in every future merge.
            self._rank_metrics.pop(rank, None)
            self._out_seq[rank] = 0
            self._in_count[rank] = 0
            if self.reconnect_grace_s > 0:
                self._out_log[rank] = deque(maxlen=_LINK_LOG_FRAMES)
            self._send_targeted_locked(
                rank, _MAGIC_WELCOME,
                json.dumps({"resume": False, "recv_count": 0}).encode(),
                log=False)
            if self._synced_params is not None:
                self._send_targeted_locked(rank, _MAGIC_PARAMS,
                                           self._synced_params)
            self._maybe_form_locked()
        self._note_fresh_life(rank)

    def _try_resume_remote(self, rank: int, sess: dict, rid: int,
                           epoch: int):
        """Resume handshake arriving through a relay (a leaf
        re-homing after its previous link — possibly a whole relay —
        died).  Same three-phase structure as _try_resume; WE + the
        downlink replay travel RD-wrapped so the relay routes them to
        exactly this child (and opens its broadcast gate)."""
        with self._lock:
            recv_count = int(sess.get("recv_count", 0))
            out_seq = self._out_seq.get(rank, 0)
            log = self._out_log.get(rank)
            rconn = self._relay_conns.get(rid)
            ok = (self.reconnect_grace_s > 0 and
                  rank not in self._lost and
                  rconn is not None and
                  sess.get("session") and
                  sess.get("session") == self._sessions.get(rank) and
                  log is not None and
                  0 <= recv_count <= out_seq and
                  out_seq - recv_count <= len(log))
            if not ok:
                logger.warning(
                    "refusing relayed resume for rank %d via relay %d "
                    "(session %s, recv_count %d/%d)", rank, rid,
                    (sess.get("session") or "?")[:8], recv_count,
                    out_seq)
                _RECONNECTS.inc(1, outcome="refused")
                if _fr.ENABLED:
                    _fr.record(_fr.RESUME, rank=0, role="coord",
                               peer=rank, outcome="refused", via=rid,
                               seq=recv_count)
                if rconn is not None:
                    try:
                        _send_frame(rconn, relay_mod.MAGIC_RELAY_DOWN,
                                    relay_mod.pack_rd(
                                        rank, _MAGIC_WELCOME,
                                        json.dumps({"resume": False}
                                                   ).encode()))
                    except OSError:
                        pass
                return
            # Phase 1: supersede the old attachment (direct conn OR a
            # previous relay/epoch); hold the rank in limbo so
            # broadcasts keep logging until the backlog is replayed.
            old = self._conns.pop(rank, None)
            self._rank_via.pop(rank, None)
            self._via_epoch.pop(rank, None)
            self._via_suspect.pop(rank, None)
            self._conn_gen[rank] = gen = \
                self._conn_gen.get(rank, 0) + 1
            self._limbo[rank] = time.monotonic()
            stream_lock = self._stream_locks.setdefault(
                rank, threading.Lock())
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        # Phase 2: wait out an in-flight frame on the old transport so
        # the uplink cursor is stable before we quote it.
        with stream_lock:
            in_count = self._in_count.get(rank, 0)
        # Phase 3: attach via the relay and replay the missed downlink.
        with self._lock:
            rconn = self._relay_conns.get(rid)
            if self._conn_gen.get(rank, 0) != gen or \
                    rank in self._lost or rconn is None or \
                    self._out_seq.get(rank, 0) - recv_count > len(log):
                logger.warning("relayed resume for rank %d aborted "
                               "mid-handshake", rank)
                _RECONNECTS.inc(1, outcome="refused")
                return
            self._rank_via[rank] = rid
            self._via_epoch[rank] = epoch
            self._last_heard[rank] = time.monotonic()
            self._limbo.pop(rank, None)
            # See _register_fresh_remote: metrics now ride the relay's
            # MA aggregate; drop any frozen direct snapshot.
            self._rank_metrics.pop(rank, None)
            try:
                _send_frame(rconn, relay_mod.MAGIC_RELAY_DOWN,
                            relay_mod.pack_rd(
                                rank, _MAGIC_WELCOME,
                                json.dumps({"resume": True,
                                            "recv_count": in_count}
                                           ).encode()))
                for ordinal, magic, payload in log:
                    if ordinal > recv_count:
                        _send_frame(rconn, relay_mod.MAGIC_RELAY_DOWN,
                                    relay_mod.pack_rd(rank, magic,
                                                      payload))
            except OSError:
                # The relay link died mid-handshake: back to limbo;
                # the leaf retries (and will climb its ancestor chain).
                self._rank_via.pop(rank, None)
                self._via_epoch.pop(rank, None)
                self._enter_limbo_locked(rank)
                return
        logger.info("rank %d re-homed via relay %d (replayed %d "
                    "downlink frames)", rank, rid,
                    self._out_seq.get(rank, 0) - recv_count)
        _RECONNECTS.inc(1, outcome="resumed")
        if _fr.ENABLED:
            _fr.record(_fr.RESUME, rank=0, role="coord", peer=rank,
                       outcome="resumed", via=rid, cyc=epoch,
                       replayed=self._out_seq.get(rank, 0) - recv_count)

    def _send_targeted_locked(self, rank: int, magic: bytes,
                              payload: bytes, log: bool = True):
        """One downlink frame to one specific rank, over whatever
        transport it is attached by — direct send, or RD-wrapped via
        its relay (caller holds self._lock)."""
        if log:
            self._log_out_locked(rank, magic, payload)
        conn = self._conns.get(rank)
        if conn is not None:
            try:
                _send_frame(conn, magic, payload)
                return True
            except OSError:
                if self.reconnect_grace_s > 0 and \
                        rank not in self._lost:
                    self._enter_limbo_locked(rank)
                else:
                    self._conns.pop(rank, None)
                return False
        rid = self._rank_via.get(rank)
        rconn = self._relay_conns.get(rid) if rid is not None else None
        if rconn is None:
            return False
        try:
            _send_frame(rconn, relay_mod.MAGIC_RELAY_DOWN,
                        relay_mod.pack_rd(rank, magic, payload))
            return True
        except OSError:
            return False  # the mux reaps the dead relay link

    def _subtree_slack(self) -> float:
        """Detection allowance for leaves behind a troubled interior
        node: before they can re-home they must first notice the
        silence themselves, bounded by their own depth-aware deadline
        (they may be deeper than the link the root observed)."""
        levels = self._plan.levels if self._plan is not None else 1
        return env_mod.depth_aware_liveness_timeout(
            self.liveness_timeout_s, levels)

    def _relay_link_down(self, rid: int, gen: int,
                         reason: Optional[str] = None):
        """A relay link died (EOF at the mux, or the liveness sweep).
        Its whole subtree enters limbo — the leaves behind it may be
        perfectly healthy and re-home within the grace window; only
        grace expiry promotes them (through the existing elastic
        eviction path).  The limbo clock carries detection slack: a
        WEDGED relay is seen by the root before its leaves can see
        the silence themselves.  With reconnects off, the subtree is
        promoted immediately (legacy fail-fast)."""
        with self._lock:
            if self._relay_gen.get(rid, 0) != gen:
                return
            self._relay_gen[rid] = gen + 1  # supersede in-flight frames
            conn = self._relay_conns.pop(rid, None)
            self._relay_metrics.pop(rid, None)
            subtree = sorted(r for r, v in self._rank_via.items()
                             if v == rid)
            stopped = self._stop.is_set()
            limbo = not stopped and self.reconnect_grace_s > 0
            slack = self._subtree_slack()
            for r in subtree:
                self._rank_via.pop(r, None)
                self._via_epoch.pop(r, None)
                if limbo and r not in self._lost:
                    self._enter_limbo_locked(r)
                    self._limbo[r] = time.monotonic() + slack
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        if stopped:
            return
        if _fr.ENABLED:
            _fr.record(_fr.RELAY_DOWN, rank=0, role="coord",
                       relay=rid, reason=reason or "connection lost",
                       subtree=list(subtree), cyc=gen)
        if subtree:
            logger.warning(
                "relay %d link down (%s): %s", rid,
                reason or "connection lost",
                ("holding %d ranks in limbo for %.1fs grace"
                 % (len(subtree), self.reconnect_grace_s)) if limbo
                else "promoting %d ranks to lost" % len(subtree))
        if limbo:
            return
        for r in subtree:
            self._count_departed(r)
            self._promote_lost(r, clean=False,
                               reason=reason or "relay link lost")

    def _handle_relay_lost(self, rid: int, payload: bytes):
        """RL notice: a relay reports children lost.  kind="silent"
        means the child ITSELF went quiet past the per-hop deadline
        (the wedged-rank case — promote, like the root's own liveness
        on direct links); kind="disconnect" is a dead child socket —
        grace window first, the leaf may simply re-home.  Entries
        carry the child-connection epoch when the reporter was the
        leaf's direct parent; epoch-less entries mean the trouble was
        INTERIOR (a sub-relay under the reporter died or went silent —
        the leaves behind it may be perfectly healthy and will
        self-detect), so they only arm a suspicion clock with
        detection slack: a leaf whose re-home already raced ahead is
        never yanked back, and one that resumes within slack + grace
        is never promoted at all."""
        try:
            notice = json.loads(payload.decode())
            entries = [(int(r), None if e is None else int(e))
                       for r, e in notice.get("ranks", [])]
            kind = notice.get("kind", "disconnect")
            reason = notice.get("reason", "")
        except (ValueError, TypeError, UnicodeDecodeError):
            logger.warning("undecodable RL notice from relay %d", rid)
            return
        if _fr.ENABLED:
            _fr.record(_fr.RELAY_LOST, rank=0, role="coord", relay=rid,
                       lost_kind=kind, reason=reason,
                       ranks=[r for r, _ in entries])
        promote = []
        now = time.monotonic()
        with self._lock:
            for rank, epoch in entries:
                if rank in self._lost:
                    continue
                if self._rank_via.get(rank) != rid:
                    continue  # re-homed elsewhere already
                if epoch is not None and \
                        self._via_epoch.get(rank) != epoch:
                    continue  # stale notice about a superseded socket
                if epoch is None:
                    # Interior trouble: the reporter cannot prove
                    # which leaves are actually affected.  Don't
                    # detach — arm a suspicion deadline (detection
                    # slack + grace) keyed to the attachment
                    # generation; a resume bumps the generation and
                    # clears it.
                    self._via_suspect[rank] = \
                        (now + self._subtree_slack() +
                         self.reconnect_grace_s,
                         self._conn_gen.get(rank, 0))
                elif kind == "silent":
                    # The LEAF itself went quiet on its direct parent:
                    # the wedged-rank case, same verdict as the root's
                    # own liveness on a direct link.
                    self._rank_via.pop(rank, None)
                    self._via_epoch.pop(rank, None)
                    promote.append(rank)
                elif self.reconnect_grace_s > 0:
                    self._rank_via.pop(rank, None)
                    self._via_epoch.pop(rank, None)
                    self._enter_limbo_locked(rank)
                else:
                    self._rank_via.pop(rank, None)
                    self._via_epoch.pop(rank, None)
                    promote.append(rank)
        for rank in promote:
            self._count_departed(rank)
            self._promote_lost(
                rank, clean=False,
                reason="relay %d reported %s (%s)" % (rid, kind,
                                                      reason))

    def _handle_metrics_aggregate(self, rid: int, payload: bytes):
        try:
            agg = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            logger.warning("undecodable MA frame from relay %d", rid)
            return
        with self._lock:
            self._relay_metrics[rid] = {
                "ranks": [int(r) for r in agg.get("ranks", [])],
                "snapshot": agg.get("snapshot") or {}}

    def _mux_close(self, token: "_LinkToken"):
        if token.kind == "relay":
            self._relay_link_down(token.ident, token.gen)
        else:
            self._rank_link_down(token.ident, token.gen, token.clean,
                                 silent=False)

    def _try_resume(self, rank: int, sess: dict, conn: socket.socket):
        """Reconnect handshake: same session inside the grace window →
        replace the link, tell the worker how many of its uplink
        frames we processed (WE frame), and replay the downlink frames
        it missed.  Anything else is refused — the worker fails over
        to the broken-membership path."""
        with self._lock:
            recv_count = int(sess.get("recv_count", 0))
            out_seq = self._out_seq.get(rank, 0)
            log = self._out_log.get(rank)
            ok = (self.reconnect_grace_s > 0 and
                  rank not in self._lost and
                  sess.get("session") and
                  sess.get("session") == self._sessions.get(rank) and
                  log is not None and
                  0 <= recv_count <= out_seq and
                  out_seq - recv_count <= len(log))
            if not ok:
                logger.warning(
                    "refusing control-channel resume for rank %d "
                    "(session %s, recv_count %d/%d, grace %s)", rank,
                    (sess.get("session") or "?")[:8], recv_count,
                    out_seq, self.reconnect_grace_s)
                _RECONNECTS.inc(1, outcome="refused")
                if _fr.ENABLED:
                    _fr.record(_fr.RESUME, rank=0, role="coord",
                               peer=rank, outcome="refused",
                               seq=recv_count)
                try:
                    _send_frame(conn, _MAGIC_WELCOME,
                                json.dumps({"resume": False}).encode())
                    conn.close()
                except OSError:
                    pass
                return
            # Phase 1 (under the lock): supersede the old link — bump
            # the generation so the old rank loop discards anything it
            # has not fully processed, and close its socket.  The rank
            # stays OUT of _conns for now: broadcasts must keep
            # accumulating in the out-log until the backlog below has
            # been replayed, or the stream would reorder.  A prior
            # relay attachment is superseded the same way (re-home
            # from a dead relay to the root).
            old = self._conns.pop(rank, None)
            self._rank_via.pop(rank, None)
            self._via_epoch.pop(rank, None)
            self._via_suspect.pop(rank, None)
            self._conn_gen[rank] = gen = \
                self._conn_gen.get(rank, 0) + 1
            # Stay in limbo (fresh timestamp) until phase 3: limbo
            # membership is what keeps broadcasts flowing into the
            # out-log during the handshake window.
            self._limbo[rank] = time.monotonic()
            stream_lock = self._stream_locks.setdefault(
                rank, threading.Lock())
        if old is not None and old is not conn:
            try:
                old.close()
            except OSError:
                pass
        # Phase 2 (stream lock, no server lock): wait out a frame the
        # old rank loop may have in flight — once it finishes (and
        # counts) or gets discarded at its gen check (un-counted, so
        # the worker's replay re-delivers it), the uplink cursor is
        # stable and the handshake can quote it.
        with stream_lock:
            in_count = self._in_count.get(rank, 0)
        # Phase 3 (server lock again): install the new conn and send
        # WE + the missed backlog atomically w.r.t. new broadcasts.
        with self._lock:
            if self._conn_gen.get(rank, 0) != gen or \
                    rank in self._lost or \
                    self._out_seq.get(rank, 0) - recv_count > len(log):
                # Superseded by a newer resume, promoted to lost, or
                # the handshake window pushed the resume point out of
                # the replay ring — refuse; the worker fails over.
                logger.warning("control-channel resume for rank %d "
                               "aborted mid-handshake", rank)
                _RECONNECTS.inc(1, outcome="refused")
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._install_conn_locked(rank, conn)
            self._limbo.pop(rank, None)
            try:
                _send_frame(conn, _MAGIC_WELCOME, json.dumps({
                    "resume": True,
                    "recv_count": in_count,
                }).encode())
                for ordinal, magic, payload in log:
                    if ordinal > recv_count:
                        _send_frame(conn, magic, payload)
            except OSError:
                # The fresh link died mid-handshake: back to limbo;
                # the worker will retry within the grace window.
                self._enter_limbo_locked(rank)
                return
            gen = self._conn_gen[rank]
        logger.info("rank %d control channel resumed (replayed %d "
                    "downlink frames)", rank, out_seq - recv_count)
        _RECONNECTS.inc(1, outcome="resumed")
        if _fr.ENABLED:
            _fr.record(_fr.RESUME, rank=0, role="coord", peer=rank,
                       outcome="resumed", cyc=gen,
                       replayed=out_seq - recv_count)
        self._serve_link(rank, conn, gen)

    def _spawn_rank_loop(self, rank: int, conn: socket.socket,
                         gen: Optional[int] = None):
        if gen is None:
            gen = self._conn_gen.get(rank, 0)
        t = threading.Thread(target=self._rank_loop,
                             args=(rank, conn, gen),
                             name=f"hvd-coord-rank{rank}", daemon=True)
        t.start()
        self._threads.append(t)

    def _sweep_period(self) -> float:
        base = self.liveness_interval_s / 2.0 if \
            self.liveness_interval_s > 0 else self.reconnect_grace_s / 4.0
        return max(min(base, 1.0), 0.05)

    def _rank_loop(self, rank: int, conn: socket.socket, gen: int = 0):
        clean = False
        silent = False

        def on_idle():
            # Poll-timeout expiry on the registered link: give up once
            # the peer has been silent past the liveness deadline (a
            # wedged rank holds its socket open — only the HB cadence
            # can expose it).
            if self._stop.is_set() or \
                    self._conn_gen.get(rank, 0) != gen:
                raise _LinkSilent("superseded")
            if time.monotonic() - self._last_heard.get(rank, 0.0) \
                    > self.liveness_timeout_s:
                raise _LinkSilent(
                    "rank %d silent for > %.1fs" %
                    (rank, self.liveness_timeout_s))

        def on_data():
            self._last_heard[rank] = time.monotonic()

        bounded = self.liveness_interval_s > 0
        try:
            while not self._stop.is_set():
                try:
                    if bounded:
                        frame = _recv_frame_bounded(conn, on_idle,
                                                    on_data)
                    else:
                        frame = _recv_frame(conn)
                except OSError:
                    frame = None
                except _LinkSilent as e:
                    if str(e) != "superseded":
                        logger.warning("liveness: %s; promoting to "
                                       "lost", e)
                        silent = True
                    return
                if frame is None:
                    return
                magic, payload = frame
                self._last_heard[rank] = time.monotonic()
                if magic in _OOS_UP:
                    # Out-of-stream: HB is a pure liveness signal, MR
                    # an absolute snapshot — neither enters the stream
                    # cursor (symmetric with the worker's up-log).
                    _FRAMES_RECV.inc(1, kind=magic.decode(
                        "ascii", "replace"))
                    if _fr.ENABLED and magic == _MAGIC_HB:
                        _fr.record(_fr.HB_RX, rank=0, role="coord",
                                   peer=rank)
                    if magic == _MAGIC_METRICS_REP:
                        self._handle_metrics_snapshot(rank, payload)
                    continue
                self.uplink_frames += 1
                if _fr.ENABLED:
                    _fr.record(_fr.FRAME_RX, rank=0, role="coord",
                               peer=rank,
                               frame=magic.decode("ascii", "replace"),
                               nbytes=len(payload),
                               seq=self._in_count.get(rank, 0) + 1,
                               cyc=gen)
                # Failpoint site: uplink frame arrival on the
                # coordinator.  drop() discards the frame (the sender's
                # tensor goes incomplete — the stall machinery must
                # attribute and fail it); error() kills this rank loop,
                # which the coordinator treats as the rank departing.
                if _fp.ENABLED and \
                        _fp.maybe_fail("coord.frame_recv",
                                       rank=rank) == "drop":
                    # An injected drop still counts as processed (the
                    # frame was lost, not deferred) — under the stream
                    # lock like the real handling below.
                    lock = self._stream_locks.get(rank)
                    if lock is not None:
                        with lock:
                            if self._conn_gen.get(rank, 0) != gen:
                                return
                            self._in_count[rank] = \
                                self._in_count.get(rank, 0) + 1
                    continue
                _FRAMES_RECV.inc(1, kind=magic.decode("ascii",
                                                      "replace"))
                _BYTES_RECV.inc(len(payload) + 6)
                # Frame handling + the stream-cursor advance are one
                # atomic unit under the per-rank stream lock: the
                # resume handshake takes the same lock to quote a
                # stable _in_count, and the generation check makes a
                # superseded loop DISCARD its in-hand frame un-counted
                # (the worker's uplink replay re-delivers it) — a
                # frame is processed exactly once, by exactly one
                # link generation.
                stream_lock = self._stream_locks.get(rank)
                if stream_lock is None:
                    return
                with stream_lock:
                    if self._conn_gen.get(rank, 0) != gen:
                        return  # superseded mid-stream
                    try:
                        if magic == _MAGIC_HITS:
                            self._handle_cache_hits(
                                rank, unpack_bits(payload))
                            continue
                        requests, shutdown = \
                            unpack_request_list(payload)
                        if shutdown:
                            clean = True
                            return
                        self._handle_requests(rank, requests)
                    finally:
                        # Stream cursor for the reconnect handshake:
                        # a frame counts once fully handled, so a
                        # resume replays exactly the unprocessed tail.
                        self._in_count[rank] = \
                            self._in_count.get(rank, 0) + 1
        finally:
            self._rank_link_down(rank, gen, clean, silent)

    def _rank_link_down(self, rank: int, gen: int, clean: bool,
                        silent: bool):
        """A rank loop exited.  Decide: superseded link (ignore), clean
        departure, transient disconnect (limbo + grace window), or
        final loss."""
        with self._lock:
            if self._conn_gen.get(rank, 0) != gen:
                return  # a resumed link took over; nothing departed
            stopped = self._stop.is_set()
            limbo = (not stopped and not clean and not silent and
                     rank not in self._lost and
                     self.reconnect_grace_s > 0)
            if limbo:
                # Socket death with reconnects enabled: hold the rank
                # in limbo — a transient TCP drop comes back within
                # the grace window and nobody else ever knows.  Its
                # departure is deferred to resume-or-expire.
                self._enter_limbo_locked(rank)
        if limbo:
            return
        self._count_departed(rank)
        if not stopped:
            self._promote_lost(rank, clean,
                               reason="liveness timeout" if silent
                               else None)

    def _promote_lost(self, rank: int, clean: bool,
                      reason: Optional[str] = None) -> bool:
        """Final, idempotent rank-loss transition: every detector
        (rank-loop exit, liveness sweep, grace expiry) funnels here;
        only the first caller runs the broken-membership machinery."""
        with self._lock:
            if rank in self._lost:
                return False
            self._lost.add(rank)
            self._limbo.pop(rank, None)
            self._rank_via.pop(rank, None)
            self._via_epoch.pop(rank, None)
            self._via_suspect.pop(rank, None)
            conn = self._conns.get(rank)
        if reason == "liveness timeout":
            _LIVENESS_TIMEOUTS.inc(1, role="coordinator")
        if conn is not None:
            try:
                conn.close()  # unblocks a rank loop stuck in recv
            except OSError:
                pass
        if _fr.ENABLED:
            _fr.record(_fr.PROMOTE, rank=0, role="coord", peer=rank,
                       clean=clean, reason=reason or "connection lost")
        self._on_rank_lost(rank, clean, reason)
        if _fr.ENABLED and not clean:
            # Dump AFTER the dead-rank notice fan-out: the ring keeps
            # recording, so deferring costs no evidence, while a file
            # write before _on_rank_lost would sit inside the very
            # detect window the MTTR drills bound.
            _fr.trigger_dump("promotion")
        return True

    def _count_departed(self, rank: int):
        """At most ONE departure per rank life: several detectors can
        observe the same death (rank-loop exit, grace expiry after a
        send-failure limbo, the sweep) and an over-count would let the
        drain tear the coordinator down under still-attached ranks."""
        with self._departed_cond:
            if rank in self._departure_counted:
                return
            self._departure_counted.add(rank)
            self._departed += 1
            self._departed_cond.notify_all()

    def _enter_limbo_locked(self, rank: int):
        if rank in self._limbo or rank in self._lost:
            return
        self._conns.pop(rank, None)
        self._limbo[rank] = time.monotonic()
        if _fr.ENABLED:
            _fr.record(_fr.LIMBO, rank=0, role="coord", peer=rank,
                       grace_s=self.reconnect_grace_s)
        logger.info("rank %d control link dropped; holding in limbo "
                    "for %.1fs grace", rank, self.reconnect_grace_s)

    # ------------------------------------------------------------------
    # liveness sweep
    # ------------------------------------------------------------------
    def _link_deadline_locked(self, key):
        """Current true liveness deadline for a heap key — a direct
        rank (int) or a relay link (("relay", rid) — depth-aware, so a
        deep subtree's forwarding latency never false-promotes it).
        None = the link is no longer tracked (caller holds
        self._lock)."""
        heard = self._last_heard.get(key)
        if heard is None:
            return None
        if isinstance(key, tuple):
            rid = key[1]
            if rid not in self._relay_conns:
                return None
            return heard + env_mod.depth_aware_liveness_timeout(
                self.liveness_timeout_s, self._relay_depth.get(rid, 1))
        if key not in self._conns:
            return None  # relay-attached ranks are watched per hop
        return heard + self.liveness_timeout_s

    def _liveness_loop(self):
        """Coordinator half of bounded-time liveness: broadcast HB
        when the downlink has been idle (so workers can bound their
        own recv waits), promote silent ranks and expired limbo ranks
        to lost, and bound the formation wait by the start timeout.
        The silent scan rides the lazy deadline heap — each tick
        visits only links whose recorded deadline lapsed, O(due)
        instead of O(world) per interval."""
        period = self._sweep_period()
        hb_armed = self.liveness_interval_s > 0
        while not self._stop.wait(period):
            now = time.monotonic()
            with self._lock:
                silent = []
                silent_relays = []
                if hb_armed:
                    if now - self._last_broadcast_t >= \
                            self.liveness_interval_s:
                        self._broadcast_frame_locked(_MAGIC_HB, b"")
                        _HEARTBEATS.inc(1, role="coordinator")
                    for key in self._lheap.due(
                            now, self._link_deadline_locked):
                        if isinstance(key, tuple):
                            silent_relays.append(
                                (key[1],
                                 self._relay_gen.get(key[1], 0)))
                        else:
                            silent.append(key)
                expired = [r for r, t in self._limbo.items()
                           if now - t > self.reconnect_grace_s]
                # Suspicion clocks (interior relay trouble reported
                # without per-socket proof): a resume bumps the
                # attachment generation and clears the suspicion;
                # deadline expiry without one promotes.
                suspect_expired = []
                for r, (deadline, gen) in \
                        list(self._via_suspect.items()):
                    if self._conn_gen.get(r, 0) != gen:
                        self._via_suspect.pop(r, None)
                    elif now > deadline:
                        self._via_suspect.pop(r, None)
                        self._rank_via.pop(r, None)
                        self._via_epoch.pop(r, None)
                        suspect_expired.append(r)
            for rid, gen in silent_relays:
                self._relay_link_down(rid, gen,
                                      reason="liveness timeout")
            for rank in suspect_expired:
                if self._promote_lost(rank, clean=False,
                                      reason="subtree suspicion "
                                             "expired"):
                    self._count_departed(rank)
            for rank in silent:
                if self._promote_lost(rank, clean=False,
                                      reason="liveness timeout"):
                    logger.warning(
                        "liveness: rank %d silent for > %.1fs; "
                        "promoted to lost", rank,
                        self.liveness_timeout_s)
            for rank in expired:
                if self._promote_lost(rank, clean=False,
                                      reason="reconnect grace "
                                             "expired"):
                    logger.warning(
                        "rank %d did not reconnect within the %.1fs "
                        "grace window; promoted to lost", rank,
                        self.reconnect_grace_s)
                    _RECONNECTS.inc(1, outcome="expired")
                    # Usually its rank loop already exited into limbo
                    # without counting a departure; when limbo was
                    # entered from a send failure the loop is still
                    # alive and will try to count again — the per-rank
                    # dedup makes either order count exactly once.
                    self._count_departed(rank)
            # Formation deadline: pre-formation there may be no stall
            # machinery armed at all — bound the wait for stragglers
            # by the start timeout so a job missing a rank fails
            # crisply instead of hanging.
            if not self._formed and \
                    now - self._started_at > env_mod.start_timeout():
                self._fail_formation_locked_entry()

    def _fail_formation_locked_entry(self):
        with self._lock:
            if self._formed:
                return
            missing = sorted(set(range(self.size)) -
                             self._attached_ranks_locked())
            # Log once even with nothing buffered: an idle formation
            # hang past the deadline must leave a trace (the sweep
            # re-evaluates every period).
            if ("__formation_deadline__",) not in self._stall_logged:
                self._stall_logged[("__formation_deadline__",)] = 1.0
                logger.error(
                    "formation deadline: ranks %s never connected "
                    "within the %.0fs start timeout", missing,
                    env_mod.start_timeout())
            pre, self._pre_formed = self._pre_formed, []
            errs = [Response(
                response_type=ResponseType.ERROR,
                tensor_names=[req.tensor_name],
                process_set_id=req.process_set_id,
                error_message=(
                    "ranks %s never connected within the %.0fs start "
                    "timeout" % (missing, env_mod.start_timeout())))
                for kind, _, payload in pre if kind == "rq"
                for req in payload]
            if errs:
                self._broadcast_locked(errs)

    def departure_counts(self):
        """(ever_connected, departed) rank-connection counters."""
        with self._departed_cond:
            return self._seen, self._departed

    # ------------------------------------------------------------------
    # cross-rank metrics aggregation
    # ------------------------------------------------------------------
    def _metrics_loop(self):
        while not self._stop.wait(self._metrics_interval_s):
            self.request_metrics()

    def request_metrics(self):
        """Broadcast one MQ poll; every worker (including rank 0's
        loopback client) answers with an MR snapshot frame."""
        with self._lock:
            self._broadcast_frame_locked(_MAGIC_METRICS_REQ, b"")

    def _handle_metrics_snapshot(self, rank: int, payload: bytes):
        try:
            snap = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            logger.warning("undecodable metrics snapshot from rank %d",
                           rank)
            return
        with self._lock:
            self._rank_metrics[rank] = snap

    def merged_metrics(self) -> Optional[dict]:
        """Sum of the latest per-rank snapshots (None until the first
        MR/MA frame lands).  ``ranks`` names the contributors, so a
        scraper can tell a partial merge from a full one.  In tree
        mode, relays pre-aggregate their subtree's MR replies into one
        MA frame each, so this merge is O(fanout) snapshots at the
        root instead of O(world)."""
        with self._lock:
            snaps = dict(self._rank_metrics)
            aggs = dict(self._relay_metrics)
        if not snaps and not aggs:
            return None
        parts = [snaps[r] for r in sorted(snaps)]
        ranks = set(snaps)
        for rid in sorted(aggs):
            parts.append(aggs[rid].get("snapshot") or {})
            ranks.update(aggs[rid].get("ranks", []))
        # Known transient: for up to one poll interval after a leaf
        # re-homes from a live relay to a direct root link, its
        # contribution may appear both in the relay's last MA
        # aggregate and as a fresh direct MR (aggregates are merged
        # sums — a single rank cannot be subtracted out).  The next
        # MQ poll re-converges; the reverse transition is cleaned
        # eagerly in the remote attach paths.
        merged = metrics.merge_snapshots(parts)
        merged["ranks"] = sorted(ranks)
        return merged

    # ------------------------------------------------------------------
    # live straggler observatory (common/straggler.py)
    # ------------------------------------------------------------------
    _STRAGGLER_REFRESH_S = 0.5

    def _straggler_loop(self):
        """Fold the MR/MA-carried per-rank phase summaries into the
        scorer and refresh scores/flags.  Runs at a fixed small
        cadence — the work is O(world) dict math, and the refresh must
        keep going during steady-state replay, when no negotiation
        arrival ever lands.  When the metrics-aggregation loop is NOT
        armed, this loop issues the MQ polls itself (every other
        tick): the observatory is self-sufficient, not parasitic on
        HOROVOD_METRICS_AGG_SECONDS."""
        sg = self._straggler
        tick = 0
        while not self._stop.wait(self._STRAGGLER_REFRESH_S):
            tick += 1
            if self._metrics_interval_s <= 0 and tick % 2 == 0:
                self.request_metrics()
            with self._lock:
                # Snapshot dicts are replaced wholesale on update
                # (never mutated in place), so holding references
                # outside the lock is safe.
                aggs = [a.get("snapshot") or {}
                        for a in self._relay_metrics.values()]
                snaps = list(self._rank_metrics.values())
            per_rank = {}
            for snap in aggs:        # relay aggregates first ...
                per_rank.update(_sg.phases_from_snapshot(snap))
            for snap in snaps:       # ... direct MR replies overlay
                per_rank.update(_sg.phases_from_snapshot(snap))
            if per_rank:
                # hvdlint: hot-ok(cold loop thread; it exists only
                # when the scorer does)
                sg.note_worker_phases(per_rank)
            sg.refresh()

    def straggler_top(self):
        """(rank, score) of the top rank currently FLAGGED slow —
        i.e. past the threshold/hysteresis gate — or None (also None
        when the observatory is disarmed).  The stall machinery
        consumes a slow-vs-dead VERDICT here, not a raw score: a
        sub-threshold residual EWMA must never steer an operator away
        from the wedged-rank diagnosis.  Raw scores stay visible in
        /status."""
        sg = self._straggler
        if sg is None:
            return None
        top = sg.top()
        if top is None or top[0] not in sg.flagged():
            return None
        return top

    def profile_digests(self) -> Dict[int, List[dict]]:
        """Per-rank top-K hot-frame digests recovered from the latest
        MR/MA snapshots (common/profiler.py rank-labeled gauges) —
        computed on demand from already-held state, cold paths only
        (/status, stall warnings, drill verdicts).  Empty when no rank
        runs with HOROVOD_PROFILE=1."""
        with self._lock:
            aggs = [a.get("snapshot") or {}
                    for a in self._relay_metrics.values()]
            snaps = list(self._rank_metrics.values())
        out: Dict[int, List[dict]] = {}
        for snap in aggs:        # relay aggregates first ...
            out.update(_prof.digest_from_snapshot(snap))
        for snap in snaps:       # ... direct MR replies overlay
            out.update(_prof.digest_from_snapshot(snap))
        return out

    def profile_root_cause(self, rank: int) -> Optional[str]:
        """One root-cause clause for ``rank`` ("failpoints:maybe_fail
        (submit lane, 72% of samples)") from its digest, or None when
        no digest has arrived — the stall inspector and the drill
        verdict attach this to their warning text."""
        text = _prof.describe_digest(self.profile_digests().get(rank))
        return text or None

    def slo_readings(self) -> Dict[int, dict]:
        """Per-rank SLO SLI/burn readings recovered from the latest
        MR/MA snapshots (common/slo.py rank-labeled gauges)."""
        with self._lock:
            aggs = [a.get("snapshot") or {}
                    for a in self._relay_metrics.values()]
            snaps = list(self._rank_metrics.values())
        out: Dict[int, dict] = {}
        for snap in aggs:
            out.update(_slo.slo_from_snapshot(snap))
        for snap in snaps:
            out.update(_slo.slo_from_snapshot(snap))
        return out

    def status(self) -> dict:
        """The /status plane's cluster view (JSON-ready): per-rank
        liveness + straggler state, negotiation counters, and queue
        shape — the live "which rank is slow RIGHT NOW" answer next
        to the post-hoc /metrics and /blackbox planes."""
        now = time.monotonic()
        with self._lock:
            ranks = {}
            for r in range(self.size):
                if r in self._lost:
                    st = "lost"
                elif r in self._limbo:
                    st = "limbo"
                elif r in self._conns or r in self._rank_via:
                    st = "alive"
                    heard = self._last_heard.get(r)
                    if self.liveness_interval_s > 0 and \
                            heard is not None and \
                            now - heard > self.liveness_timeout_s:
                        # Connected but silent past the deadline: the
                        # SIGSTOP/GIL-deadlock shape, pre-promotion.
                        st = "wedged"
                else:
                    st = "unknown"
                d = {"state": st}
                heard = self._last_heard.get(r)
                if heard is not None:
                    d["last_heard_age_s"] = round(now - heard, 3)
                rid = self._rank_via.get(r)
                if rid is not None:
                    d["via_relay"] = rid
                ranks[str(r)] = d
            out = {
                "size": self.size,
                "formed": self._formed,
                "broken": self._broken,
                "pending_tensors": len(self._table.entries),
                "pending_barriers": len(self._barriers),
                "negotiation": dict(self.stats),
            }
        sg = self._straggler
        if sg is not None:
            snap = sg.snapshot()
            out["straggler"] = snap
            for r_s, d in ranks.items():
                score = snap["scores"].get(r_s)
                if score is not None:
                    d["score"] = score
                    d["slow"] = int(r_s) in snap["flagged"]
        digests = self.profile_digests()
        if digests:
            # Why-is-it-slow: per-rank digests (k-ordered) plus a
            # one-line hot_frame on each rank row so hvdtop can show
            # the dominant frame without a second request.
            out["profile"] = {str(r): entries
                              for r, entries in digests.items()}
            for r_s, d in ranks.items():
                entries = digests.get(int(r_s))
                if entries:
                    d["hot_frame"] = "%s [%s]" % (
                        entries[0]["frame"], entries[0]["lane"])
        slo_map = self.slo_readings()
        if slo_map:
            out["slo"] = {str(r): v for r, v in slo_map.items()}
        out["ranks"] = ranks
        return out

    def _on_rank_lost(self, rank: int, clean: bool,
                      reason: Optional[str] = None):
        """A rank departed mid-run.  In elastic mode, pending
        negotiations can never complete: fail them on every surviving
        rank so blocked synchronize() calls raise HorovodInternalError
        and unwind to the elastic retry loop (the analog of the
        reference's collective errors on peer failure,
        common/exceptions.py:18 semantics)."""
        if self._on_rank_lost_hook is not None:
            # Out-of-band notification (rank 0 publishes it to the
            # elastic rendezvous KV so the driver can evict the host
            # of a wedged-but-alive worker process).
            try:
                self._on_rank_lost_hook(rank, clean, reason)
            except Exception:
                logger.warning("rank-lost hook failed", exc_info=True)
        if self._straggler is not None:
            # Same eviction contract as the metrics snapshot below: a
            # lost rank's frozen lag/wait EWMAs (and slow flag) must
            # stop contributing, or it could read as "top straggler"
            # forever — the dead-as-slow misdiagnosis.
            self._straggler.drop_rank(rank)
        with self._lock:
            # A departed rank must stop contributing to the merged
            # metrics view: its frozen last snapshot would otherwise be
            # summed into every future merge, and the ``ranks``
            # contributor list would keep advertising a dead process.
            self._rank_metrics.pop(rank, None)
            if self.tune_session is not None and \
                    self.tune_session.active:
                # A rank died MID-SEARCH: abort to default knobs in
                # one atomic PA — a proposal half-applied across the
                # surviving ranks would poison the post-recovery
                # world's same-schedule contract.  Survivors (elastic)
                # or the teardown path (static) all see the same final
                # default-knob payload.
                self.tune_session.abort("rank_lost")
                self._drain_tune_locked()
        if not self.elastic:
            return
        with self._lock:
            self._conns.pop(rank, None)
            self._broken = True
            # Keys are (psid, name); the ERROR responses must carry
            # BOTH — workers pop their tensor-table entries by
            # (name, psid), so an error missing the psid never reaches
            # a non-global set's blocked submitter.  Pre-formation
            # buffered requests fail too: their submitters are blocked
            # just the same.
            pending = list(self._table.entries.keys()) + \
                list(self._barriers.keys()) + \
                [(req.process_set_id, req.tensor_name)
                 for kind, _, payload in self._pre_formed
                 if kind == "rq" for req in payload]
            self._pre_formed.clear()
            self._table.entries.clear()
            self._barriers.clear()
            self._barrier_members.clear()
            self._first_seen.clear()
            self._bit_only.clear()
            if self._straggler is not None:
                # Every in-flight negotiation just failed: its partial
                # arrival sets are not lag samples.
                self._straggler.reset_pending()
            msg = (f"rank {rank} left the job "
                   f"({'clean' if clean else reason or 'connection lost'}); "
                   "membership changed")
            logger.info("elastic coordinator: %s", msg)
            responses = [Response(
                response_type=ResponseType.ERROR, tensor_names=[name],
                process_set_id=psid,
                error_message=msg) for psid, name in pending]
            if responses:
                self._broadcast_locked(responses)
            # Abort broadcast: a worker with NO pending eager
            # negotiation (e.g. blocked inside a TF in-graph
            # collective, or compute-bound) must still learn the
            # membership broke NOW — while this coordinator is alive —
            # so it can unwind and disconnect its jax client before
            # rank 0 takes the coordination service down (leader loss
            # under an attached client is process-fatal).
            self._broadcast_frame_locked(_MAGIC_ABORT, msg.encode())

    def _broadcast_locked(self, responses: List[Response]):
        self._broadcast_frame_locked(_MAGIC_RESP,
                                     pack_response_list(responses))

    @staticmethod
    def _required_for(req: Request) -> int:
        return len(req.process_set_ranks) if req.process_set_ranks else 0

    def _joined_count_for(self, req: Request) -> int:
        if req.process_set_ranks:
            return len(self._joined & set(req.process_set_ranks))
        return len(self._joined)

    def _scan_complete(self) -> List[Tuple[str, List[Request]]]:
        """Re-scan the message table for tensors completed by a rank
        joining (the reference fires pending tensors when join
        participation changes, controller.cc:254-308)."""
        ready: List[Tuple[tuple, List[Request]]] = []
        for key in list(self._table.entries.keys()):
            msgs = self._table.entries[key]
            if not msgs:
                continue
            required = self._required_for(msgs[0]) or self.size
            if len(msgs) + self._joined_count_for(msgs[0]) >= required:
                self._table.pop(key)
                self._first_seen.pop(key, None)
                ready.append((key, msgs))
        return ready

    def _handle_requests(self, rank: int, requests: List[Request]):
        with self._lock:
            # _broken outranks the formation gate: after an elastic
            # rank loss during formation the gate can never open, and
            # buffering would hide the failure from the submitter
            # forever — _process's broken branch errors it instead.
            if not self._formed and not self._broken:
                self._pre_formed.append(("rq", rank, requests))
                return
            self._dispatch_uplink_locked("rq", rank, requests)

    def _handle_cache_hits(self, rank: int, bits: List[int]):
        """Fast-path uplink: each bit is a full request the worker
        elided because its cached signature still matches (reference:
        CacheCoordinator::sync)."""
        with self._lock:
            if not self._formed and not self._broken:
                # Unreachable with a fresh cache (no bit precedes the
                # first RS, which the gate itself blocks) — buffered
                # for defense in depth.
                self._pre_formed.append(("ch", rank, bits))
                return
            self._dispatch_uplink_locked("ch", rank, bits)

    def _dispatch_uplink_locked(self, kind: str, rank: int, payload):
        """Route one uplink frame ("rq" request list / "ch" bit list)
        into _process; shared by the live path and the formation-gate
        drain (caller holds self._lock)."""
        if kind == "rq":
            items = [(req, False) for req in payload]
        else:
            items = self._resolve_hits(rank, payload)
        if items:
            self._process(rank, items)

    def _resolve_hits(self, rank: int, bits: List[int]
                      ) -> List[Tuple[Request, bool]]:
        """Resolve CH bits into requests (caller holds self._lock)."""
        items: List[Tuple[Request, bool]] = []
        for bit in bits:
            resolved = self._cache.resolve_bit(bit)
            if resolved is None:
                # Only possible if >TOMBSTONE_CAP evictions raced one
                # in-flight frame — effectively unreachable; the
                # sender's tensor would hang, so fail loudly.
                logger.error(
                    "unresolvable cache bit %d from rank %d; "
                    "protocol desync", bit, rank)
                self._broadcast_locked([Response(
                    response_type=ResponseType.ERROR,
                    tensor_names=[f"__cache_bit_{bit}"],
                    error_message="response-cache protocol desync")])
                continue
            live, key, sig, sizes, gid = resolved
            name = key[1]  # cache keys are (psid, name)
            first_dim = None
            if sig[7] == int(RequestType.ALLGATHER) and sizes:
                # tensor_sizes are in GROUP order: index by the
                # rank's position in the process set when one is
                # given; a rank outside the set gets NO override
                # (mirrors the native coordinator).
                psr = sig[8]
                if psr:
                    idx = psr.index(rank) if rank in psr else -1
                else:
                    idx = rank
                if 0 <= idx < len(sizes):
                    first_dim = sizes[idx]
            req = signature_to_request(sig, rank, name, first_dim)
            req.group_id = gid
            # A tombstoned bit still counts as a contribution, but
            # forces the full (renegotiation) path.
            items.append((req, live))
        return items

    def _process(self, rank: int, items: List[Tuple[Request, bool]]):
        """Accumulate; fire fused broadcasts with everything that became
        ready (single-threaded per coordinator via the lock: ordering of
        broadcast frames is the global execution order).  Caller holds
        self._lock."""
        if self._broken:
            # Membership already changed this epoch: every new
            # request fails fast so submitters unwind promptly.
            self._broadcast_locked([Response(
                response_type=ResponseType.ERROR,
                tensor_names=[req.tensor_name],
                process_set_id=req.process_set_id,
                error_message="membership changed; collective "
                              "cannot complete")
                for req, _ in items])
            return
        # Every per-tensor dict below is keyed by (process_set_id,
        # name): the same name may be live on two process sets at once
        # (reference analog: per-set controllers in process_set.h).
        # Straggler attribution rides the arrival order this loop
        # already observes (and used to discard): one timestamp per
        # uplink frame is plenty — cross-rank order is what matters,
        # intra-frame order is meaningless.
        sg = self._straggler
        sg_now = time.monotonic() if sg is not None else 0.0
        ready: List[Tuple[tuple, Optional[List[Request]], Optional[Response]]] = []
        for req, from_cache in items:
            name = req.tensor_name
            key = MessageTable.key(req)
            n = 1
            for d in req.tensor_shape:
                n *= d
            self._elem_cache[key] = n
            self._group_ids[key] = req.group_id
            if req.request_type == RequestType.JOIN:
                self._joined.add(rank)
                self._last_joined = rank
                if len(self._joined) == self.size:
                    ready.append((key, None, Response(
                        response_type=ResponseType.JOIN,
                        tensor_names=["join"],
                        last_joined_rank=self._last_joined)))
                    self._joined.clear()
                else:
                    # Tensors waiting only on the joined rank are
                    # now complete (zeros substituted).  Force the
                    # full-negotiation path: a cached response would
                    # carry the joined rank's old contribution (e.g.
                    # nonzero allgather row counts) whereas
                    # construct_response records zeros for it.
                    for ckey, msgs in self._scan_complete():
                        self._bit_only[ckey] = False
                        if sg is not None:
                            # Join-forced completion: the arrival set
                            # is missing the joined rank — not a fair
                            # lag sample.  Drop, don't attribute.
                            sg.note_abandon(ckey)
                        ready.append((ckey, msgs, None))
                continue
            if req.request_type == RequestType.BARRIER:
                required = self._required_for(req) or self.size
                arrived = self._barriers.setdefault(key, set())
                arrived.add(rank)
                # Barriers live outside the message table, so they need
                # their own stall clock: a rank dying at a barrier must
                # surface through attribution + shutdown like any other
                # collective, not hang the arrived ranks forever.
                self._first_seen.setdefault(key, time.monotonic())
                self._barrier_members[key] = req.process_set_ranks
                if len(arrived) >= required:
                    del self._barriers[key]
                    self._barrier_members.pop(key, None)
                    self._first_seen.pop(key, None)
                    ready.append((key, None, Response(
                        response_type=ResponseType.BARRIER,
                        tensor_names=[name],
                        process_set_id=req.process_set_id,
                        process_set_ranks=req.process_set_ranks)))
                continue
            if not from_cache:
                self._bit_only[key] = False
                if self._cache.has(key):
                    # Signature changed on some rank (or it evicted
                    # locally): renegotiate from scratch so the cached
                    # response can never serve a stale shape/dtype
                    # (reference: INVALID → eviction,
                    # response_cache.cc:49-87).
                    bit = self._cache.evict_name(key)
                    if bit is not None:
                        self._pending_evictions.append(bit)
            else:
                self._bit_only.setdefault(key, True)
            required = self._required_for(req) or self.size
            self._first_seen.setdefault(key, time.monotonic())
            if sg is not None:
                sg.note_arrival(key, rank, sg_now)
            complete = self._table.increment(
                req, required,
                joined_count=self._joined_count_for(req))
            if self.timeline:
                self.timeline.negotiate_rank_ready(name, rank)
            if complete:
                msgs = self._table.pop(key)
                self._first_seen.pop(key, None)
                if sg is not None:
                    sg.note_complete(key)
                ready.append((key, msgs, None))
        if not ready:
            self._flush_evictions_locked()
            return

        # Partition completed tensors: pure-bit rounds ride the compact
        # CB frame; anything else is (re)negotiated and re-cached.  A
        # grouped submission must not straddle the two frames (group
        # atomicity): if any member renegotiates, every member of that
        # group is demoted to the full path this round.
        full_groups: Set[int] = set()
        for key, msgs, direct in ready:
            if direct is None and not (
                    self._bit_only.get(key, False) and
                    self._cache.has(key)):
                gid = self._group_ids.get(key, -1)
                if gid >= 0:
                    full_groups.add(gid)
        hit_responses: List[Response] = []
        full_responses: List[Response] = []
        sig_by_key: Dict[tuple, tuple] = {}
        for key, msgs, direct in ready:
            if direct is not None:
                full_responses.append(direct)
                continue
            bit_only = self._bit_only.pop(key, False)
            self._stall_logged.pop(key, None)
            ent = self._cache.get(key)
            # While any rank is joined, cached responses are stale for
            # it (renegotiation substitutes zeros for joined ranks) —
            # bypass the fast path entirely.
            if bit_only and ent is not None and not self._joined and \
                    self._group_ids.get(key, -1) not in full_groups:
                hit_responses.append(ent[1])
                self.stats["fast_tensors"] += 1
                _COORD_TENSORS.inc(1, path="fast")
                continue
            resp = construct_response(msgs[0].tensor_name, msgs,
                                      self.size, self._joined)
            sig_by_key[key] = request_signature(msgs[0])
            full_responses.append(resp)
            self.stats["negotiated_tensors"] += 1
            _COORD_TENSORS.inc(1, path="negotiated")
            self._cache.clear_tombstones_for(key)

        nbytes = 0
        sess = self.tune_session
        # Cycle-class of this round: any ALLTOALL response makes it
        # sparse (the DLRM embedding exchange — per-step splits, never
        # cacheable, so alltoall can only appear among the negotiated
        # responses); everything else is dense.  The tuning session
        # scores and searches the two classes independently, and the
        # fusion threshold each fuse below uses is the CLASS's live
        # proposal (hit batches are cacheable-only, hence dense).
        sparse_round = any(
            r.response_type == ResponseType.ALLTOALL
            for r in full_responses)
        if hit_responses:
            fused_hits = fuse_responses(
                hit_responses, self._elem_cache,
                sess.fusion_threshold_for(False) if sess is not None
                else self.fusion_threshold,
                self._group_ids)
            batches = [[self._cache.get((fr.process_set_id, n))[0]
                        for n in fr.tensor_names]
                       for fr in fused_hits]
            payload = pack_bit_batches(batches)
            self._broadcast_frame_locked(_MAGIC_CACHE, payload)
            self.stats["fast_rounds"] += 1
            _ROUNDS.inc(1, kind="fast")
            nbytes += sum(self._elem_cache.get((fr.process_set_id, n),
                                               0) *
                          dtype_size(fr.tensor_type)
                          for fr in fused_hits for n in fr.tensor_names)
        if full_responses:
            fused = fuse_responses(full_responses, self._elem_cache,
                                   sess.fusion_threshold_for(sparse_round)
                                   if sess is not None
                                   else self.fusion_threshold,
                                   self._group_ids)
            if self._cache.enabled:
                self._assign_cache_bits(fused, sig_by_key)
            self._flush_evictions_locked()
            self._broadcast_locked(fused)
            self.stats["full_rounds"] += 1
            _ROUNDS.inc(1, kind="full")
            nbytes += sum(self._elem_cache.get((fr.process_set_id, n),
                                               0) *
                          dtype_size(fr.tensor_type)
                          for fr in fused for n in fr.tensor_names)
        else:
            self._flush_evictions_locked()
        if sess is not None:
            sess.observe_round(nbytes, sparse=sparse_round)
            self._drain_tune_locked()
        if self.param_manager is not None:
            if self.param_manager.active:
                self.param_manager.record_step(nbytes)
                self.fusion_threshold = \
                    self.param_manager.fusion_threshold_bytes
            if self.param_manager.params_version != \
                    self._synced_params_version:
                self._sync_tuned_params_locked()

    def _drain_tune_locked(self):
        """Broadcast any queued tuning announcement (knob proposal,
        freeze, abort) as a PA frame under the server lock, and keep
        it as the registration-replay payload so late joiners and
        resumed sessions see the current knob state.  Broadcasting
        under the lock positions the frame identically in every
        worker's response stream — all ranks flip knobs at the same
        cycle boundary."""
        payload = self.tune_session.take_announcement()
        if payload is None:
            return
        data = json.dumps(payload).encode()
        self._synced_params = data
        self._broadcast_frame_locked(_MAGIC_PARAMS, data)

    def _sync_tuned_params_locked(self):
        """Announce the autotuner's categorical knobs to every worker
        via a PA frame (the reference broadcasts tuned params through
        the controller, controller.cc:39-53).  Broadcast under the
        server lock positions the frame identically in every worker's
        response stream, so all ranks flip between the same two fused
        batches."""
        pm = self.param_manager
        params = pm.categorical_params
        self._synced_params_version = pm.params_version
        cache_on = bool(params["cache"])
        if cache_on != self._cache.enabled:
            self._pending_evictions.extend(
                self._cache.set_enabled(cache_on))
            self._flush_evictions_locked()
        payload = json.dumps({
            "hierarchical": bool(params["hierarchical"]),
            "cache": cache_on,
            "fusion": int(self.fusion_threshold),
            # Lifecycle bit for the replay tracker: the legacy
            # autotuner's convergence releases the replay hold exactly
            # like a tune-session freeze — replay gates on "tuning
            # still active", not on the blanket autotune knob.
            "tuning_active": bool(pm.active),
        }).encode()
        self._synced_params = payload
        self._broadcast_frame_locked(_MAGIC_PARAMS, payload)

    def _assign_cache_bits(self, fused: List[Response],
                           sig_by_key: Dict[tuple, tuple]):
        """Seed the cache from freshly negotiated responses and stamp
        the coordinator-assigned bits onto the wire."""
        pending = set(self._table.entries.keys())
        for resp in fused:
            if resp.response_type not in CACHEABLE or resp.error_message:
                continue
            parts = split_response(resp, self.size)
            bits = []
            for i, name in enumerate(resp.tensor_names):
                key = (resp.process_set_id, name)
                sig = sig_by_key.get(key)
                if sig is None:
                    bits.append(-1)
                    continue
                bit, evicted = self._cache.insert(
                    key, parts[i], sig, self._group_ids.get(key, -1),
                    pending)
                bits.append(bit)
                self._pending_evictions.extend(evicted)
            resp.cache_bits = bits

    def _flush_evictions_locked(self):
        if self._pending_evictions:
            self._broadcast_frame_locked(
                _MAGIC_EVICT, pack_bits(self._pending_evictions))
            self._pending_evictions = []

    def _broadcast_frame_locked(self, magic: bytes, payload: bytes):
        # Failpoint site: coordinator broadcast fan-out.  drop()
        # suppresses one whole downlink frame — every rank misses it,
        # the negotiation wedges, and the stall shutdown must fail the
        # collective rather than hang the job.  error() degrades to
        # the same drop semantics: a raise here would propagate into
        # whichever caller holds the lock (rank loops, the stall and
        # metrics threads) and permanently kill the very machinery
        # that bounds the fault.
        if _fp.ENABLED:
            try:
                if _fp.maybe_fail("coord.broadcast") == "drop":
                    return
            except _fp.FailpointError:
                logger.warning("failpoint coord.broadcast: injected "
                               "error; dropping the frame")
                return
        self._last_broadcast_t = time.monotonic()
        t0 = time.perf_counter_ns()
        sent = 0
        if self._tree:
            # Relay tree: ONE send per root link — O(fanout) relay
            # links plus the direct leaves (rank 0's loopback and any
            # re-homed stragglers); relays fan the frame down.  The
            # out-log still records per RANK (relays are stateless),
            # so any leaf can resume against the root after its relay
            # dies.
            if self.reconnect_grace_s > 0:
                for r in set(self._conns) | set(self._rank_via) | \
                        set(self._limbo):
                    self._log_out_locked(r, magic, payload)
            dead = []
            for r, conn in self._conns.items():
                try:
                    _send_frame(conn, magic, payload)
                    sent += 1
                except OSError:
                    dead.append(r)
            for r in dead:
                if self.reconnect_grace_s > 0 and \
                        r not in self._lost:
                    self._enter_limbo_locked(r)
                else:
                    self._conns.pop(r, None)
            for rid, conn in self._relay_conns.items():
                try:
                    _send_frame(conn, magic, payload)
                    sent += 1
                except OSError:
                    pass  # the mux reaps the dead relay link
        elif self.reconnect_grace_s > 0:
            # Limbo ranks have no live socket but stay in the fan-out:
            # the frame enters their out-log, so a resume inside the
            # grace window replays it and the rank never falls out of
            # lockstep.
            for r in list(self._conns.keys()) + \
                    list(self._limbo.keys()):
                if self._send_to_rank_locked(r, magic, payload):
                    sent += 1
        else:
            # Reconnects off: the original direct fan-out (this is the
            # hottest coordinator path — no per-rank indirection).
            dead = []
            for r, conn in self._conns.items():
                try:
                    _send_frame(conn, magic, payload)
                    sent += 1
                except OSError:
                    dead.append(r)
            for r in dead:
                self._conns.pop(r, None)
        self.bcast_ns += time.perf_counter_ns() - t0
        self.bcast_sends += sent
        if _fr.ENABLED:
            _fr.record(_fr.FRAME_TX, rank=0, role="coord",
                       frame=magic.decode("ascii", "replace"),
                       nbytes=len(payload), fanout=sent)
        if sent:
            # Coordinator fan-out is the dominant control-plane send
            # volume on rank 0 — account it next to the worker-side
            # counters (same registry, same process).
            _FRAMES_SENT.inc(sent, kind=magic.decode("ascii", "replace"))
            _BYTES_SENT.inc(sent * (len(payload) + 6))

    def _send_to_rank_locked(self, rank: int, magic: bytes,
                             payload: bytes) -> bool:
        """One downlink frame to one rank: out-log bookkeeping and the
        send in lockstep (caller holds self._lock).  A send failure
        with reconnects enabled parks the rank in limbo instead of
        dropping it."""
        self._log_out_locked(rank, magic, payload)
        conn = self._conns.get(rank)
        if conn is None:
            return False
        try:
            _send_frame(conn, magic, payload)
            return True
        except OSError:
            if self.reconnect_grace_s > 0 and rank not in self._lost:
                self._enter_limbo_locked(rank)
            else:
                self._conns.pop(rank, None)
            return False

    def _log_out_locked(self, rank: int, magic: bytes, payload: bytes):
        if self.reconnect_grace_s <= 0 or magic in _OOS_DOWN:
            return
        log = self._out_log.get(rank)
        if log is None:
            return
        self._out_seq[rank] = self._out_seq.get(rank, 0) + 1
        log.append((self._out_seq[rank], magic, payload))

    # ------------------------------------------------------------------
    # stall attribution (reference stall_inspector.{h,cc}: rank-0 names
    # which ranks submitted a tensor and which did not)
    # ------------------------------------------------------------------
    def _check_formation_stall(self):
        """Pre-formation requests never enter the message table, so
        the per-tensor stall report is blind to a rank that crashes
        before connecting — attribute THAT stall here, and past the
        shutdown threshold fail the buffered collectives (the failure
        class the stall machinery exists for)."""
        with self._lock:
            if self._formed or not self._pre_formed:
                return
            age = time.monotonic() - self._started_at
            if age < self._stall_warning_s:
                return
            attached = self._attached_ranks_locked()
            missing = sorted(set(range(self.size)) - attached)
            last = self._stall_logged.get(("__formation__",), 0.0)
            if age - last >= self._stall_warning_s or last == 0:
                self._stall_logged[("__formation__",)] = age
                logger.warning(
                    "STALL: waiting for ranks %s to connect for %.0fs "
                    "(%d/%d registered, %d requests buffered)",
                    missing, age, len(attached), self.size,
                    len(self._pre_formed))
            if 0 < self._stall_shutdown_s <= age:
                pre, self._pre_formed = self._pre_formed, []
                errs = [Response(
                    response_type=ResponseType.ERROR,
                    tensor_names=[req.tensor_name],
                    process_set_id=req.process_set_id,
                    error_message=(
                        "ranks %s never connected within %.0fs"
                        % (missing, self._stall_shutdown_s)))
                    for kind, _, payload in pre if kind == "rq"
                    for req in payload]
                if errs:
                    self._broadcast_locked(errs)

    def stall_report(self) -> List[Tuple[str, List[int], List[int], float]]:
        """(tensor, submitted_ranks, missing_ranks, age_s) for every
        tensor — including pending barriers — stuck longer than the
        warning threshold."""
        now = time.monotonic()
        out = []
        with self._lock:
            for key, msgs in self._table.entries.items():
                if not msgs:
                    continue
                ts = self._first_seen.get(key)
                if ts is None or now - ts < self._stall_warning_s:
                    continue
                submitted = sorted({m.request_rank for m in msgs})
                members = msgs[0].process_set_ranks or range(self.size)
                missing = sorted(set(members) - set(submitted)
                                 - self._joined)
                out.append((key, submitted, missing, now - ts))
            for key, arrived in self._barriers.items():
                ts = self._first_seen.get(key)
                if ts is None or now - ts < self._stall_warning_s:
                    continue
                members = self._barrier_members.get(key) or \
                    range(self.size)
                missing = sorted(set(members) - arrived - self._joined)
                out.append((key, sorted(arrived), missing, now - ts))
        return out

    def _stall_loop(self):
        interval = max(min(self._stall_warning_s / 2.0, 10.0), 0.25)
        while not self._stop.wait(interval):
            self._check_formation_stall()
            for key, submitted, missing, age in self.stall_report():
                name = key[1]
                last = self._stall_logged.get(key, 0.0)
                if age - last < self._stall_warning_s and last > 0:
                    continue
                self._stall_logged[key] = age
                # Flight-recorder attribution: the warning names what
                # the implicated tensor last DID (frame/replay/submit
                # events), not just which ranks are missing.
                recent = _fr.recent_for_tensors([name]) \
                    if _fr.ENABLED else []
                # Straggler attribution: "everyone blocked on rank 3"
                # (the top straggler IS among the missing — slow, not
                # dead; the pre-emptive-migration case) reads very
                # differently from "no straggler signal" (suspect a
                # wedged rank or the coordinator's own links).
                top = self.straggler_top()
                if top is not None and top[0] in missing:
                    sg_note = (" Missing ranks appear blocked behind "
                               "straggler rank %d (score %.1f): slow,"
                               " not dead." % top)
                    # Root cause when the profiler digests carry one:
                    # name the frame the implicated rank is stuck in
                    # (common/profiler.py), turning "rank 3 is slow"
                    # into "rank 3 is slow in shard_io:fsync".
                    cause = self.profile_root_cause(top[0])
                    if cause:
                        sg_note += (" Rank %d dominant frame: %s."
                                    % (top[0], cause))
                elif top is not None:
                    sg_note = (" Top straggler rank %d (score %.1f) "
                               "is not among the missing ranks; "
                               "suspect a wedged rank or link "
                               "instead." % top)
                else:
                    sg_note = ""
                logger.warning(
                    "STALL: tensor %s — ranks %s submitted, ranks %s "
                    "have not, for %.0fs. One or more ranks may be "
                    "running a different graph or have hung.%s%s",
                    name, submitted, missing, age, sg_note,
                    (" Last recorder events: %s" % recent)
                    if recent else "")
                if _fr.ENABLED:
                    _fr.record(_fr.STALL, rank=0, role="coord",
                               tensor=name, submitted=submitted,
                               missing=missing, age_s=round(age, 3),
                               straggler=list(top) if top else None)
                if _prof.ENABLED:
                    # Why-is-it-slow: freeze the profiler window at
                    # the moment the coordinator surfaced the stall.
                    _prof.trigger_capture(
                        "stall", "tensor %s missing %s" % (
                            name, missing))
                if 0 < self._stall_shutdown_s <= age:
                    logger.error(
                        "stalled tensor %s exceeded shutdown threshold "
                        "(%.0fs); failing the collective", name,
                        self._stall_shutdown_s)
                    if _fr.ENABLED:
                        _fr.trigger_dump("stall_shutdown")
                    with self._lock:
                        msgs = self._table.pop(key)
                        if self._straggler is not None:
                            self._straggler.note_abandon(key)
                        # Barriers stall too (tracked outside the
                        # message table); fail the arrived ranks the
                        # same way.
                        stalled_barrier = \
                            self._barriers.pop(key, None) is not None
                        self._barrier_members.pop(key, None)
                        self._first_seen.pop(key, None)
                        self._bit_only.pop(key, None)
                        if msgs or stalled_barrier:
                            self._broadcast_locked([Response(
                                response_type=ResponseType.ERROR,
                                tensor_names=[name],
                                process_set_id=key[0],
                                error_message=(
                                    f"collective {name} stalled: ranks "
                                    f"{missing} never submitted it "
                                    f"within {self._stall_shutdown_s:.0f}"
                                    "s"))])

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values()) + \
                list(self._relay_conns.values())
            self._conns.clear()
            self._relay_conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._mux is not None:
            self._mux.stop()


class NetworkController(Controller):
    """Per-rank controller client.  Rank 0 additionally hosts the
    CoordinatorServer (mirroring the reference where rank 0 is both a
    worker and the coordinator, controller.cc:69-449)."""

    def __init__(self, state):
        super().__init__(state)
        self.server: Optional[CoordinatorServer] = None
        self._closing = False
        self._broken_err: Optional[Exception] = None
        # Worker-side response cache (fast-path uplink/downlink); the
        # coordinator owns bit assignment, we just follow the RS frames.
        self.cache = WorkerResponseCache(state.knobs.cache_capacity)
        self._sent_sigs: Dict[tuple, tuple] = {}  # (psid, name) -> sig
        # Bounded cache-seed diagnostics (read on desync only).
        from collections import deque
        self._seed_log = deque(maxlen=64)
        self.stats = {"rq_frames": 0, "ch_frames": 0, "rs_frames": 0,
                      "cb_frames": 0, "ev_frames": 0, "pa_frames": 0,
                      "mr_frames": 0,
                      "bytes_sent": 0, "bytes_recv": 0}
        # PA params stashed until the batches received before them have
        # executed (applied at the next compute_response_list entry).
        self._pending_params: Optional[dict] = None
        # Runtime hook for tuned worker knobs (cycle time, coalescing,
        # replay warmup/hold): _apply_params forwards the decoded PA
        # payload so the runtime flips its knobs at the frame's
        # position in the response stream.
        self._params_hook = None
        # True while an MR (metrics snapshot) reply thread is in
        # flight; written only by the recv thread.
        self._mr_sending = False
        # Straggler-observatory phase collector (wired by the runtime;
        # its EWMAs are folded into rank-labeled gauges right before
        # each MR reply so the per-rank summaries ride the existing
        # metrics frames).
        self._phase_collector = None
        self._replay_observer = None
        # --- self-healing control plane (docs/failure_recovery.md) ---
        # _selfheal is THE hot-path gate: None when both liveness and
        # reconnect are disabled, so the steady-state submit path pays
        # exactly one attribute check (the failpoints.ENABLED
        # precedent, asserted by tests/test_liveness.py).
        knobs = state.knobs
        self._liveness_interval_s = knobs.liveness_interval_s
        # Relay tree (HOROVOD_COORD_FANOUT, common/relay.py): this
        # rank's parent may be a relay; re-homing walks the ancestor
        # chain toward the root.  The coordinator-silence deadline is
        # depth-aware — each relay hop adds forwarding latency (and
        # one possible failover) between the root's heartbeat and us.
        self._fanout = getattr(knobs, "coord_fanout", 0)
        self._plan = relay_mod.plan_tree(self.size, self._fanout) \
            if self._fanout > 0 else None
        self._hops = self._plan.leaf_hops(self.rank) \
            if (self._plan is not None and self.rank != 0) else 0
        self._liveness_timeout_s = env_mod.depth_aware_liveness_timeout(
            knobs.liveness_timeout_s, self._hops)
        self._grace_s = knobs.reconnect_grace_s
        self._hosted_relays: List = []
        self._selfheal = True if (self._liveness_interval_s > 0 or
                                  self._grace_s > 0) else None
        self._session_id = "%016x" % random.getrandbits(64)
        self._up_log: deque = deque(maxlen=_LINK_LOG_FRAMES)
        self._up_count = 0          # uplink frames sent this session
        self._recv_count = 0        # downlink frames processed
        self._last_recv_t = time.monotonic()
        self._last_uplink_t = time.monotonic()
        self._wedged = False        # harness SIGSTOP analog
        self._half_open = False     # harness peer-vanishes analog
        self._hb_stop = threading.Event()
        self._hb_thread = None
        addr = env_mod.env_str_opt(CONTROLLER_ADDR_ENV)
        if self.rank == 0:
            port = 0
            if addr and ":" in addr:
                port = int(addr.rsplit(":", 1)[1])
            param_manager = None
            tune_session = None
            if state.knobs.tune:
                # Autotune-then-freeze (horovod_tpu/tune): a valid
                # profile at HOROVOD_TUNE_PROFILE means the search
                # already ran — build a pre-frozen session (per-class
                # thresholds from the artifact, startup announcement
                # says tuning_active=false) so restarts and elastic
                # resizes skip the re-search.  Takes precedence over
                # the legacy HOROVOD_AUTOTUNE path.
                from ..tune.session import TuningSession
                # The SAME parsed artifact Knobs.from_env adopted —
                # never a second read of the file, which could race a
                # concurrent freeze replacing it and hand the session
                # different knobs than the ones already applied.
                prof = getattr(state.knobs, "tune_profile_obj", None)
                if prof is not None:
                    tune_session = TuningSession.from_profile(
                        state.knobs, self.size, prof,
                        profile_path=state.knobs.tune_profile)
                else:
                    tune_session = TuningSession(
                        state.knobs, self.size,
                        profile_path=state.knobs.tune_profile)
                state.tune_session = tune_session
            elif state.knobs.autotune:
                from .parameter_manager import ParameterManager
                param_manager = ParameterManager(
                    warmup_samples=state.knobs.autotune_warmup_samples,
                    steps_per_sample=state.knobs.autotune_steps_per_sample,
                    bayes_opt_max_samples=(
                        state.knobs.autotune_bayes_opt_max_samples),
                    gp_noise=state.knobs.autotune_gaussian_process_noise,
                    initial_fusion_bytes=(
                        state.knobs.fusion_threshold_bytes),
                    initial_cycle_ms=state.knobs.cycle_time_ms,
                    # Explicit env settings pin the categorical dims.
                    fixed_hierarchical=state.knobs.hierarchical_allreduce,
                    fixed_cache=(False if state.knobs.cache_capacity == 0
                                 else None),
                    log_path=state.knobs.autotune_log)
                state.parameter_manager = param_manager
            self.server = self._make_server(state, port, param_manager,
                                            tune_session)
            self._publish_actual_addr(addr, self.server.port)
            host = "127.0.0.1"
            self._addr = (host, self.server.port)
            self._host_relays(state, addr)
        else:
            resolved = self._resolve_addr(addr)
            if not resolved:
                raise RuntimeError(
                    f"{CONTROLLER_ADDR_ENV} must be set for multi-process "
                    "runs (the launcher sets it automatically).")
            host, port = resolved.rsplit(":", 1)
            self._addr = (host, int(port))
            self._host_relays(state, resolved)
        self._addr_chain = self._build_addr_chain()
        self._sock = self._connect()
        self._recv_buf: "queue.Queue" = queue.Queue()
        self._on_receive = None
        self._on_response = None
        self._send_lock = threading.Lock()
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name="hvd-ctrl-recv", daemon=True)
        self._recv_thread.start()
        if self._liveness_interval_s > 0:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="hvd-ctrl-heartbeat",
                daemon=True)
            self._hb_thread.start()

    def set_receive_callback(self, fn):
        """Called (from the recv thread) whenever a frame is queued —
        the runtime wires its wake event here so response pickup is
        event-driven instead of a poll."""
        self._on_receive = fn

    def set_phase_collector(self, collector):
        """Runtime hook (common/straggler.py): the per-runtime phase
        collector whose EWMAs each MR reply publishes under this
        rank's label."""
        self._phase_collector = collector

    def set_replay_observer(self, observer):
        """Steady-state replay hook (common/replay.py): the recv thread
        reports response/eviction/param frames so the tracker can
        detect converged cycles and exit replay on invalidation.
        Observation happens BEFORE delivery, so by the time a blocked
        submitter wakes the tracker has already recorded its response."""
        self._replay_observer = observer

    def set_response_callback(self, fn):
        """Direct dispatch: the recv thread executes each response by
        calling ``fn(response)`` the moment its frame is decoded,
        instead of queuing for the background thread.  On a 1-core
        host every thread handoff is a context switch, so cutting the
        recv->queue->background hop removes a fixed ~0.1-0.2 ms from
        per-op latency (the reference instead pays its fixed cycle
        sleep, operations.cc:587).  Ordering is inherited from the
        coordinator's broadcast order because the recv loop is the
        single, sequential consumer of the socket.  PA markers apply
        in-stream between executed batches for free."""
        self._on_response = fn

    def _make_server(self, state, port, param_manager,
                     tune_session=None):
        """Prefer the native C++ coordinator (horovod_tpu/native); fall
        back to the Python CoordinatorServer.  The Python server is
        also used when a timeline is active (negotiation spans are
        recorded coordinator-side), when cross-rank metrics
        aggregation is requested (MQ/MR frames), and while the
        autotuner runs (the
        parameter manager scores real per-round byte counts in-line and
        announces categorical knobs via PA frames — higher-fidelity
        than the native counter-polling path it replaces)."""
        allow_ephemeral = self._rendezvous_client() is not None
        stall_warn = 0.0 if state.knobs.stall_check_disable else \
            state.knobs.stall_warning_time_s
        # When the user EXPLICITLY set HOROVOD_TPU_NATIVE to a truthy
        # value, a missing/broken native build is an error, not a
        # silent fallback — otherwise native-path tests pass vacuously
        # against the Python coordinator.
        strict_native = env_mod.env_bool("HOROVOD_TPU_NATIVE")
        if strict_native and param_manager is not None:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_AUTOTUNE=1: the autotuner requires the Python "
                "coordinator (in-line scoring + PA parameter frames). "
                "Unset one of the two.")
        if strict_native and tune_session is not None:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_TUNE=1: autotune-then-freeze requires the "
                "Python coordinator (per-class round scoring + PA knob "
                "frames).  Run the frozen knobs through plain env "
                "variables instead, or unset one of the two.")
        metrics_interval = state.knobs.metrics_agg_interval_s
        if strict_native and metrics_interval > 0:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_METRICS_AGG_SECONDS>0: cross-rank metrics "
                "aggregation requires the Python coordinator (MQ/MR "
                "frames).  Unset one of the two.")
        # Armed failpoints pin the Python coordinator: the native C++
        # coordinator carries no injection sites, and a fault schedule
        # that silently skipped its coord.*/worker.* rules would report
        # a vacuous pass.  Strict-native + failpoints is a config error.
        if strict_native and _fp.ENABLED:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_FAILPOINTS: fault injection requires the "
                "Python coordinator.  Unset one of the two.")
        # The self-healing control plane (HB liveness, reconnect grace)
        # is Python-coordinator-only: the native server treats any
        # non-CH/RQ frame as a departed rank, so heartbeats would kill
        # every link.  Same gating rule as the other Python-only
        # features above (documented in docs/failure_recovery.md).
        selfheal = state.knobs.liveness_interval_s > 0 or \
            state.knobs.reconnect_grace_s > 0
        if strict_native and selfheal:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_LIVENESS_INTERVAL/HOROVOD_RECONNECT_GRACE: "
                "the self-healing control plane requires the Python "
                "coordinator (HB/WE frames).  Unset one of the two.")
        # The relay tree is Python-coordinator-only too: the native
        # server has no RB/RD/RL relay frames, so a relay registering
        # against it would kill the link.  Same gating rule as the
        # other Python-only features above.
        tree = getattr(state.knobs, "coord_fanout", 0) > 0
        if strict_native and tree:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_COORD_FANOUT>0: the relay-tree control plane "
                "requires the Python coordinator (relay frames).  "
                "Unset one of the two.")
        # The straggler observatory is Python-coordinator-only too:
        # arrival attribution lives in the Python _process loop and
        # the worker phase summaries ride MR frames the native server
        # does not speak.  Same gating rule as the features above.
        if strict_native and _sg.ENABLED:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_STRAGGLER=1: the straggler observatory "
                "requires the Python coordinator (CH/RQ arrival "
                "attribution + MR phase frames).  Unset one of the "
                "two.")
        if state.timeline is None and param_manager is None and \
                tune_session is None and \
                metrics_interval <= 0 and not _fp.ENABLED and \
                not selfheal and not tree and not _sg.ENABLED:
            try:
                from ..native import NativeCoordinatorServer, available
                if strict_native and not available():
                    raise RuntimeError(
                        "HOROVOD_TPU_NATIVE is set but the native "
                        "coordinator could not be built/loaded")
                if available():
                    return NativeCoordinatorServer(
                        self.size, port=port,
                        fusion_threshold=(
                            state.knobs.fusion_threshold_bytes),
                        elastic=state.knobs.elastic,
                        allow_ephemeral_fallback=allow_ephemeral,
                        cache_capacity=state.knobs.cache_capacity,
                        stall_warning_time_s=stall_warn,
                        stall_shutdown_time_s=(
                            state.knobs.stall_shutdown_time_s))
            except OSError:
                raise   # bind failure: same semantics as Python server
            except Exception:
                if strict_native:
                    raise
                logger.warning("native coordinator unavailable; using "
                               "the Python coordinator", exc_info=True)
        if _slo.ENABLED:
            # Rank 0 hosts the coordinator: its SLO burn alerts become
            # the job-level KV notice the elastic driver folds into
            # ElasticPolicy.Signals (None client → no hook, local
            # alerting still works).
            _slo.set_burn_hook(self._make_slo_publisher())
        return CoordinatorServer(
            self.size, port=port,
            fusion_threshold=state.knobs.fusion_threshold_bytes,
            timeline=state.timeline,
            elastic=state.knobs.elastic,
            allow_ephemeral_fallback=allow_ephemeral,
            param_manager=param_manager,
            cache_capacity=state.knobs.cache_capacity,
            stall_warning_time_s=stall_warn,
            stall_shutdown_time_s=state.knobs.stall_shutdown_time_s,
            metrics_interval_s=metrics_interval,
            liveness_interval_s=state.knobs.liveness_interval_s,
            liveness_timeout_s=state.knobs.liveness_timeout_s,
            reconnect_grace_s=state.knobs.reconnect_grace_s,
            registration_timeout_s=state.knobs.registration_timeout_s,
            fanout=getattr(state.knobs, "coord_fanout", 0),
            on_rank_lost=self._make_rank_lost_publisher(state),
            tune_session=tune_session,
            on_rank_slow=self._make_rank_slow_publisher())

    def _make_rank_lost_publisher(self, state):
        """Rank-0 hook: publish non-clean rank-lost promotions to the
        elastic rendezvous KV so the driver can evict the host of a
        wedged-but-alive worker process (its monitor would otherwise
        wait forever for an exit code)."""
        if not state.knobs.elastic:
            return None
        client = self._rendezvous_client()
        if client is None:
            return None

        def publish(rank, reason, _client=client):
            try:
                from ..runner.elastic.worker import current_epoch
                epoch = current_epoch()
            except Exception:
                epoch = 0
            try:
                # Per-rank key: two ranks lost in the same driver poll
                # interval must not overwrite each other's notice.
                _client.put("elastic", "lost-%d" % rank, json.dumps({
                    "rank": rank,
                    "reason": reason or "connection lost",
                    "epoch": epoch,
                }).encode())
            except OSError:
                logger.warning("could not publish the lost-rank "
                               "notice to the rendezvous KV",
                               exc_info=True)

        def hook(rank, clean, reason):
            if clean:
                return
            # Publish OFF the calling thread: the hook runs from frame
            # dispatch (in tree mode the single mux recv thread; in
            # flat mode a rank loop) and a slow/partitioned rendezvous
            # would otherwise block control-plane processing for the
            # client's full HTTP timeout.
            threading.Thread(target=publish, args=(rank, reason),
                             name="hvd-lost-publish", daemon=True
                             ).start()

        return hook

    def _make_rank_slow_publisher(self):
        """Rank-0 hook: publish straggler-threshold crossings to the
        rendezvous KV under ``elastic/slow/<rank>`` — the consumable
        signal for verdict-driven pre-emptive migration (ROADMAP item
        5c; the slow-rank mirror of the ``elastic/lost-<rank>``
        promotion notice).  Wired here; the elastic driver does not
        act on it yet."""
        client = self._rendezvous_client()
        if client is None:
            return None

        def publish(rank, score, _client=client):
            try:
                _client.put("elastic", "slow-%d" % rank, json.dumps({
                    "rank": rank,
                    "score": round(score, 3),
                    "wall": time.time(),
                }).encode())
            except OSError:
                logger.warning("could not publish the slow-rank "
                               "notice to the rendezvous KV",
                               exc_info=True)

        def hook(rank, score):
            # Off the scorer's refresh loop: a slow/partitioned
            # rendezvous must not stall score refreshes for the
            # client's full HTTP timeout.
            threading.Thread(target=publish, args=(rank, score),
                             name="hvd-slow-publish", daemon=True
                             ).start()

        return hook

    def _make_slo_publisher(self):
        """Rank-0 hook: publish this job's SLO reading to the
        rendezvous KV under ``elastic/slo`` whenever the plane
        evaluates a burn alert — the load-trend signal
        ``runner/elastic/driver.py`` folds into
        ``ElasticPolicy.Signals`` (cycle_time_s / steps_per_s;
        consumed read-only until the SLO-driven controller lands,
        ROADMAP item 4).  One key, not per-rank: the SLIs are a
        job-level reading taken on the coordinator."""
        client = self._rendezvous_client()
        if client is None:
            return None

        def publish(alert, _client=client):
            reading = _slo.signals_reading()
            try:
                _client.put("elastic", "slo", json.dumps({
                    "sli": alert.get("sli"),
                    "burn_short": alert.get("burn_short"),
                    "burn_long": alert.get("burn_long"),
                    "steps_per_s": reading.get("steps_per_s"),
                    "cycle_time_s": reading.get("cycle_time_s"),
                    "wall": time.time(),
                }).encode())
            except OSError:
                logger.warning("could not publish the SLO notice to "
                               "the rendezvous KV", exc_info=True)

        def hook(alert):
            # Off the evaluator loop, same as the slow-rank publisher.
            threading.Thread(target=publish, args=(alert,),
                             name="hvd-slo-publish", daemon=True
                             ).start()

        return hook

    @staticmethod
    def _rendezvous_client():
        from ..runner.http_server import RendezvousClient
        addr = env_mod.env_str_opt(env_mod.HOROVOD_RENDEZVOUS_ADDR)
        port = env_mod.env_str_opt(env_mod.HOROVOD_RENDEZVOUS_PORT)
        if not addr or not port:
            return None
        return RendezvousClient(addr, int(port))

    def _ctrl_scope(self) -> str:
        # Per-epoch scope so elastic re-inits don't read a stale addr.
        epoch = env_mod.env_str(CONTROLLER_ADDR_ENV, "")
        return f"controller.{epoch}"

    def _publish_actual_addr(self, env_addr, actual_port):
        """Rank 0: publish the actually-bound controller address to the
        rendezvous KV store (guards against the launcher-chosen port
        being taken by the time rank 0 binds it)."""
        client = self._rendezvous_client()
        if client is None:
            return
        host = env_addr.rsplit(":", 1)[0] if env_addr else "127.0.0.1"
        try:
            client.put(self._ctrl_scope(), "addr",
                       f"{host}:{actual_port}".encode())
        except OSError:
            logger.warning("could not publish controller addr to "
                           "rendezvous", exc_info=True)

    def _resolve_addr(self, env_addr):
        """Workers: prefer the rendezvous-published address; fall back
        to the env contract (used when no rendezvous server exists)."""
        client = self._rendezvous_client()
        if client is not None:
            timeout_s = env_mod.start_timeout()
            try:
                raw = client.wait_get(self._ctrl_scope(), "addr",
                                      timeout=timeout_s)
                return raw.decode()
            except (OSError, TimeoutError):
                logger.warning("rendezvous controller-addr lookup "
                               "failed; using env value")
        return env_addr

    def _host_relays(self, state, env_addr):
        """Launcher runs: designated host ranks start their relays
        in-process and publish the addresses through the rendezvous
        KV.  Skipped entirely when HOROVOD_RELAY_ADDRS is set (a
        harness/launcher owns the relays) or when there is no KV to
        publish through (leaves then fall back to direct root links —
        degraded but correct)."""
        if self._plan is None or relay_mod.relay_addr_map():
            return
        mine = self._plan.relays_hosted_by(self.rank)
        if not mine:
            return
        client = self._rendezvous_client()
        if client is None:
            logger.warning(
                "HOROVOD_COORD_FANOUT=%d requested but neither "
                "HOROVOD_RELAY_ADDRS nor a rendezvous KV is "
                "available to place relays; every rank will link "
                "directly to rank 0 (flat star)", self._fanout)
            return
        # Publish relays at THIS worker's address, not the
        # coordinator's: on a multi-host launch the hosting rank lives
        # on its own machine (the launcher's hostname contract names
        # it); env_addr's host is only right for rank 0 — and for
        # single-host runs, where everything shares it.
        host = env_mod.env_str_opt(env_mod.HOROVOD_HOSTNAME)
        if not host:
            host = env_addr.rsplit(":", 1)[0] if env_addr \
                else "127.0.0.1"
        root_addr = "%s:%d" % self._addr if self.rank == 0 \
            else (env_addr or "")
        local: Dict[int, str] = {}
        knobs = self.state.knobs
        for rid in mine:  # highest level first: parents before kids
            chain = []
            for anc in self._plan.relay_ancestors(rid):
                if anc in local:
                    chain.append(local[anc])
                    continue
                try:
                    chain.append(client.wait_get(
                        self._ctrl_scope(), "relay.%d" % anc,
                        timeout=env_mod.start_timeout()).decode())
                except (OSError, TimeoutError):
                    logger.warning("relay %d: ancestor %d address "
                                   "never appeared; climbing past it",
                                   rid, anc)
            if root_addr:
                chain.append(root_addr)
            try:
                rs = relay_mod.RelayServer(
                    rid, chain, bind_addr="0.0.0.0",
                    liveness_interval_s=knobs.liveness_interval_s,
                    liveness_timeout_s=knobs.liveness_timeout_s,
                    registration_timeout_s=(
                        knobs.registration_timeout_s),
                    depth_below=self._plan.relays[rid].depth_below)
            except (OSError, ConnectionError):
                logger.warning("could not start relay %d; its leaves "
                               "will fall back to ancestors",
                               rid, exc_info=True)
                continue
            addr = "%s:%d" % (host, rs.port)
            local[rid] = addr
            self._hosted_relays.append(rs)
            try:
                client.put(self._ctrl_scope(), "relay.%d" % rid,
                           addr.encode())
            except OSError:
                logger.warning("could not publish relay %d address",
                               rid, exc_info=True)

    def _build_addr_chain(self) -> List[Tuple[str, int]]:
        """This rank's connection targets, nearest parent first, the
        root always last: [relay, grandparent relay, ..., root].
        Re-homing escalates through it (docs/failure_recovery.md)."""
        chain: List[Tuple[str, int]] = []
        if self._plan is not None and self.rank != 0:
            amap = relay_mod.relay_addr_map()
            client = None if amap else self._rendezvous_client()
            for rid in self._plan.ancestors_of_leaf(self.rank):
                addr = amap.get(rid)
                if addr is None and client is not None:
                    try:
                        addr = client.wait_get(
                            self._ctrl_scope(), "relay.%d" % rid,
                            timeout=env_mod.start_timeout()).decode()
                    except (OSError, TimeoutError):
                        addr = None
                if addr and ":" in addr:
                    h, p = addr.rsplit(":", 1)
                    chain.append((h, int(p)))
                else:
                    logger.warning("no address for relay %d; rank %d "
                                   "will skip that hop", rid,
                                   self.rank)
        chain.append(self._addr)
        return chain

    def _registration_payload(self, resume: bool) -> bytes:
        """Rank id, plus the session blob when the self-healing channel
        is on.  The native coordinator reads only the first 4 bytes, so
        the extended form stays wire-compatible."""
        head = struct.pack("<i", self.rank)
        if self._selfheal is None:
            return head
        return head + json.dumps({
            "session": self._session_id,
            "resume": resume,
            "recv_count": self._recv_count,
        }).encode()

    def _poll_period_s(self) -> float:
        return max(min(self._liveness_timeout_s / 4.0, 1.0), 0.05)

    def _arm_sock(self, s: socket.socket):
        """Recv deadline: with liveness on, the recv loop polls at a
        fraction of the liveness timeout (the pre-liveness
        settimeout(None) blocked forever on a wedged coordinator)."""
        if self._liveness_interval_s > 0:
            s.settimeout(self._poll_period_s())
        else:
            # hvdlint: bounded-by(liveness off is the documented
            # legacy opt-out: a wedged coordinator is then caught only
            # by the stall inspector; HOROVOD_LIVENESS_INTERVAL>0
            # arms the poll timeout above)
            s.settimeout(None)

    def _connect(self) -> socket.socket:
        # The start timeout bounds the wait for the coordinator (or
        # this rank's relay) to come up (launcher --start-timeout;
        # reference launch.py start_timeout contract).  With a relay
        # tree, the assigned relay is preferred for a patience window
        # before escalating toward the root — an immediate root
        # fallback at startup would quietly flatten the topology.
        timeout_s = env_mod.start_timeout()
        start = time.monotonic()
        deadline = start + timeout_s
        # Wall-clock patience for the assigned relay (NOT an attempt
        # count: connection-refused fails in microseconds, and relay
        # bring-up on another host can legitimately take a while —
        # serial RelayServer starts gated on KV address waits).
        patience_s = min(max(timeout_s / 4.0, 5.0), 30.0) \
            if len(self._addr_chain) > 1 else 0.0
        last_err = None
        while time.monotonic() < deadline:
            reach = 1 if time.monotonic() - start < patience_s \
                else len(self._addr_chain)
            for addr in self._addr_chain[:reach]:
                try:
                    s = socket.create_connection(addr, timeout=5.0)
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                    self._arm_sock(s)
                    _send_frame(
                        s, _MAGIC_REQ,
                        self._registration_payload(resume=False))
                    self._last_recv_t = time.monotonic()
                    return s
                except OSError as e:
                    last_err = e
            time.sleep(0.2)
        raise ConnectionError(
            f"could not reach coordinator via {self._addr_chain}: "
            f"{last_err}")

    def _reconnect(self) -> bool:
        """The control socket died mid-incarnation: retry with
        jittered exponential backoff inside the grace window, resume
        the session (coordinator replays the downlink we missed, we
        replay the uplink it never processed), and hand the new socket
        back to the recv loop.  With a relay tree, retries *re-home*:
        the first attempts go to the assigned relay (a blip heals in
        place), then escalate up the ancestor chain — grandparent
        relay, finally the root, which holds every rank's session
        state (relays are stateless, so the resume is identical at any
        hop).  Returns False when the window expires or the
        coordinator refuses the resume — the caller then runs the
        legacy broken-membership path."""
        deadline = time.monotonic() + self._grace_s
        try:
            self._sock.close()
        except OSError:
            pass
        attempt = 0
        chain = self._addr_chain
        target_idx = 0
        # Hops that accepted TCP but never answered the WE handshake
        # are wedged (SIGSTOP'd relay: its accept thread lives, its
        # forwarding is frozen) — skip them for the rest of this
        # episode instead of burning the grace window on them again.
        wedged_hops = set()
        while not self._closing:
            attempt += 1
            backoff = min(0.05 * (2 ** (attempt - 1)), 1.0)
            backoff *= 0.5 + random.random()  # jitter: avoid stampede
            if time.monotonic() + backoff >= deadline:
                break
            time.sleep(backoff)
            # Escalate one hop every other failed attempt; the last
            # chain entry is always the root.
            target_idx = min((attempt - 1) // 2, len(chain) - 1)
            while target_idx in wedged_hops and \
                    target_idx < len(chain) - 1:
                target_idx += 1
            try:
                s = socket.create_connection(chain[target_idx],
                                             timeout=2.0)
            except OSError:
                continue
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The WE answer from a healthy path arrives in
                # milliseconds; cap the wait well below the grace
                # window so one unresponsive (wedged) hop leaves
                # enough budget to climb to an ancestor.
                s.settimeout(max(0.25, min(
                    2.0, self._grace_s / 3.0,
                    deadline - time.monotonic())))
                _send_frame(s, _MAGIC_REQ,
                            self._registration_payload(resume=True))
                try:
                    frame = _recv_frame(s)
                except socket.timeout:
                    # Branding the hop wedged is deliberately eager: a
                    # false positive (the hop was healthy but the root
                    # was backlogged replaying a thundering herd of
                    # resumes) only costs climbing to an ancestor —
                    # sessions live on the root, so a resume succeeds
                    # identically at ANY hop, and the root itself is
                    # never skippable.
                    if target_idx < len(chain) - 1:
                        wedged_hops.add(target_idx)
                        logger.warning(
                            "resume via hop %d accepted but never "
                            "answered; climbing the ancestor chain",
                            target_idx)
                    s.close()
                    continue
                if frame is None or frame[0] != _MAGIC_WELCOME:
                    s.close()
                    continue
                info = json.loads(frame[1].decode())
                if not info.get("resume"):
                    # The coordinator cannot resume this session (out
                    # of its replay window, or the rank was already
                    # promoted to lost) — fail over, don't retry.
                    s.close()
                    logger.warning("control-channel resume refused by "
                                   "the coordinator")
                    _RECONNECTS.inc(1, outcome="failed")
                    return False
                acked = int(info.get("recv_count", 0))
                with self._send_lock:
                    if not (0 <= acked <= self._up_count and
                            self._up_count - acked <= len(self._up_log)):
                        s.close()
                        _RECONNECTS.inc(1, outcome="failed")
                        return False
                    for ordinal, magic, payload in self._up_log:
                        if ordinal > acked:
                            _send_frame(s, magic, payload)
                    self._arm_sock(s)
                    self._sock = s
                self._last_recv_t = time.monotonic()
                logger.info(
                    "control channel resumed after %d attempt(s) via "
                    "%s (replayed %d uplink frames)", attempt,
                    "parent" if target_idx == 0 else
                    ("ancestor %d" % target_idx),
                    self._up_count - acked)
                _RECONNECTS.inc(1, outcome="resumed")
                if _fr.ENABLED:
                    _fr.record(_fr.RESUME, rank=self.rank,
                               role="worker", outcome="resumed",
                               hop=target_idx, attempts=attempt,
                               replayed=self._up_count - acked,
                               sess=self._session_id[:8])
                if len(chain) > 1:
                    relay_mod._REHOMES.inc(
                        1, outcome="resumed_parent" if target_idx == 0
                        else "resumed_ancestor")
                    if _fr.ENABLED:
                        _fr.record(_fr.REHOME, rank=self.rank,
                                   role="worker", hop=target_idx,
                                   outcome="resumed")
                return True
            except (OSError, ValueError):
                try:
                    s.close()
                except OSError:
                    pass
                continue
        if not self._closing:
            logger.warning("control channel could not be re-established "
                           "within the %.1fs grace window", self._grace_s)
            _RECONNECTS.inc(1, outcome="failed")
            if _fr.ENABLED:
                _fr.record(_fr.RESUME, rank=self.rank, role="worker",
                           outcome="failed", attempts=attempt,
                           sess=self._session_id[:8])
            if len(chain) > 1:
                relay_mod._REHOMES.inc(1, outcome="failed")
        return False

    # ------------------------------------------------------------------
    # worker-side liveness (HB heartbeats)
    # ------------------------------------------------------------------
    def _hb_loop(self):
        """Heartbeat timer: an HB frame rides the uplink whenever no
        real traffic has flowed for a liveness interval (piggyback
        suppression — steady-state training sends zero HBs).  Also the
        evaluation point for the net.* / worker.wedge failpoints,
        which model exactly the silent failures liveness exists to
        catch."""
        period = max(self._liveness_interval_s / 2.0, 0.05)
        suppressed = False  # flight-recorder state flip, not per-tick
        while not self._hb_stop.wait(period):
            if self._closing:
                return
            if _fp.ENABLED:
                # worker.wedge: partition(Ns) wedges this rank like a
                # SIGSTOP — heartbeats stop, downlink processing stops
                # (the recv loop checks the same window), the socket
                # stays open.  Only coordinator liveness can see it.
                if _fp.maybe_fail("worker.wedge",
                                  rank=self.rank) == "drop":
                    continue
                # net.half_open: the peer vanishes without FIN — stop
                # all sends permanently, keep the socket.
                if _fp.maybe_fail("net.half_open",
                                  rank=self.rank) == "drop":
                    self._half_open = True
                # net.conn_drop: a transient TCP drop — sever the live
                # socket; the reconnect path must heal it.
                if _fp.maybe_fail("net.conn_drop",
                                  rank=self.rank) == "drop":
                    self.debug_sever()
                    continue
            if self._wedged or self._half_open:
                continue
            if time.monotonic() - self._last_uplink_t < \
                    self._liveness_interval_s:
                # Real traffic is flowing; HB suppressed.  Record the
                # state FLIP only (never per tick): a postmortem can
                # tell "quiet because piggybacked" from "quiet because
                # dead" without the ring filling with suppressions.
                if _fr.ENABLED and not suppressed:
                    _fr.record(_fr.HB_TX, rank=self.rank,
                               role="worker", suppressed=True)
                suppressed = True
                continue
            suppressed = False
            if _fp.ENABLED and _fp.maybe_fail(
                    "net.heartbeat_drop", rank=self.rank) == "drop":
                continue
            try:
                with self._send_lock:
                    self._send_frame_counted_locked(
                        _MAGIC_HB, b"", "hb_frames", "HB")
                _HEARTBEATS.inc(1, role="worker")
            except OSError:
                pass  # the recv loop owns link-death handling

    # Harness hooks (tools/chaos_soak.py, tests/test_liveness.py):
    # deterministic in-process analogs of SIGSTOP and a TCP RST.
    def debug_wedge(self, on: bool = True):
        """Freeze this rank's control plane without closing anything:
        no heartbeats, no downlink processing — what SIGSTOP looks
        like from the coordinator's side."""
        self._wedged = on

    def debug_half_open(self, on: bool = True):
        """Peer-drops-without-FIN analog: sends stop, reads stop, the
        socket object stays open so the coordinator gets no EOF."""
        self._half_open = on

    def debug_sever(self):
        """Abruptly close the live control socket (transient network
        drop); with reconnect enabled the channel must self-heal.
        shutdown() first: close() alone does not release the kernel's
        file reference while a thread is blocked inside recv, so no
        FIN would reach the peer until that thread woke."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def set_broken_callback(self, fn):
        """Called once (from the recv thread) when the control-plane
        connection dies mid-incarnation, so the runtime can fail fast
        instead of waiting for the next submission to notice."""
        self._on_broken = fn

    def _set_broken(self, err):
        self._broken_err = err
        if _fr.ENABLED:
            _fr.record(_fr.FATAL, rank=self.rank, role="worker",
                       error=str(err)[:200],
                       sess=self._session_id[:8])
            _fr.trigger_dump("fatal")
        if self._replay_observer is not None:
            self._replay_observer.on_broken()
        cb = getattr(self, "_on_broken", None)
        if cb is not None:
            try:
                cb(err)
            except Exception:
                logger.warning("broken-callback failed", exc_info=True)

    def _on_recv_idle(self):
        if self._closing:
            raise _LinkSilent("closing")
        if self._wedged or self._half_open:
            return  # a wedged rank detects nothing (SIGSTOP analog)
        if time.monotonic() - self._last_recv_t > \
                self._liveness_timeout_s:
            raise _LinkSilent(
                "coordinator silent for > %.1fs"
                % self._liveness_timeout_s)

    def _note_recv_data(self):
        self._last_recv_t = time.monotonic()

    def _recv_loop(self):
        bounded = self._liveness_interval_s > 0
        while True:
            silent = False
            try:
                if bounded:
                    frame = _recv_frame_bounded(self._sock,
                                                self._on_recv_idle,
                                                self._note_recv_data)
                else:
                    frame = _recv_frame(self._sock)
            except OSError:
                frame = None
            except _LinkSilent as e:
                frame = None
                if not self._closing:
                    silent = True
                    logger.warning("liveness: %s", e)
                    _LIVENESS_TIMEOUTS.inc(1, role="worker")
            if frame is None:
                if self._closing:
                    return
                # Transient-fault tolerance: try to resume the session
                # inside the grace window before declaring the world
                # broken.  A silent coordinator may just be a half-open
                # socket on our side — a successful resume proves it.
                if self._grace_s > 0 and self._reconnect():
                    continue
                if self._closing:
                    return  # teardown raced the reconnect window
                from .exceptions import HorovodInternalError
                self._set_broken(HorovodInternalError(
                    "coordinator liveness timeout (no control-plane "
                    "traffic for %.1fs)" % self._liveness_timeout_s
                    if silent else
                    "connection to the coordinator was lost "
                    "(membership changed or rank 0 exited)"))
                return
            magic, payload = frame
            while (self._wedged or self._half_open) and \
                    not self._closing:
                time.sleep(0.02)  # SIGSTOP analog: hold the frame
            if _fp.ENABLED:
                # worker.wedge=partition(Ns): downlink processing
                # pauses for the window, like the harness flag above.
                while not self._closing and _fp.maybe_fail(
                        "worker.wedge", rank=self.rank) == "drop":
                    time.sleep(0.02)
            self._last_recv_t = time.monotonic()
            if magic == _MAGIC_WELCOME:
                continue  # handshake-only frame; not part of the stream
            if magic == _MAGIC_HB:
                _FRAMES_RECV.inc(1, kind="HB")
                if _fr.ENABLED:
                    _fr.record(_fr.HB_RX, rank=self.rank,
                               role="worker")
                continue  # out-of-stream liveness signal
            if magic == _MAGIC_METRICS_REQ:
                # Out-of-stream metrics poll: absolute snapshots need
                # no replay, and keeping MQ/MR outside the stream
                # cursors is what lets relays aggregate them.
                _FRAMES_RECV.inc(1, kind="MQ")
                self._spawn_metrics_reply()
                continue
            self._recv_count += 1
            # Failpoint site: downlink frame arrival on a worker.
            # drop() loses one response/cache frame for THIS rank only
            # — it falls out of lockstep with its peers, the shape of
            # desync the coordinator's attribution must survive.
            # error() models a corrupt/dead downlink and must route
            # through the broken-connection path: letting it kill this
            # recv thread bare would leave blocked synchronize()
            # callers hanging with no one to fail them.
            if _fp.ENABLED:
                try:
                    if _fp.maybe_fail("worker.frame_recv",
                                      rank=self.rank) == "drop":
                        continue
                except _fp.FailpointError as e:
                    from .exceptions import HorovodInternalError
                    self._set_broken(HorovodInternalError(str(e)))
                    return
            self.stats["bytes_recv"] += len(payload) + 6
            _BYTES_RECV.inc(len(payload) + 6)
            _FRAMES_RECV.inc(1, kind=magic.decode("ascii", "replace"))
            if _fr.ENABLED:
                _fr.record(_fr.FRAME_RX, rank=self.rank, role="worker",
                           frame=magic.decode("ascii", "replace"),
                           nbytes=len(payload) + 6,
                           seq=self._recv_count,
                           sess=self._session_id[:8])
            if magic == _MAGIC_CACHE:
                self.stats["cb_frames"] += 1
                batches = unpack_bit_batches(payload)
                responses = self._reconstruct_cached(batches)
                if responses is None:
                    return  # desync; _broken_err set
                if self._replay_observer is not None:
                    self._replay_observer.on_responses(
                        "cb", list(zip(responses, batches)))
                self._deliver(responses)
                continue
            if magic == _MAGIC_EVICT:
                self.stats["ev_frames"] += 1
                bits = unpack_bits(payload)
                self.cache.evict_bits(bits)
                if self._replay_observer is not None:
                    self._replay_observer.on_evictions(bits)
                continue
            if magic == _MAGIC_ABORT:
                from .exceptions import HorovodInternalError
                self._set_broken(HorovodInternalError(
                    payload.decode(errors="replace")))
                return
            if magic == _MAGIC_PARAMS:
                self.stats["pa_frames"] += 1
                params = json.loads(payload.decode())
                if self._replay_observer is not None:
                    self._replay_observer.on_params()
                if self._on_response is not None:
                    # Direct dispatch executes batches in-stream, so
                    # by the time the PA frame is decoded every batch
                    # received before it has already run — apply
                    # immediately; every worker flips knobs at the
                    # same logical point.
                    self._apply_params(params)
                else:
                    # Queued as an in-stream marker: the runtime
                    # applies it exactly between the batches it
                    # arrived between (hierarchical on/off changes the
                    # compiled collective program — a half-flipped
                    # world would hang).
                    self._recv_buf.put(("PA", params))
                    if self._on_receive is not None:
                        self._on_receive()
                continue
            if magic == _MAGIC_RESP:
                self.stats["rs_frames"] += 1
                responses, _ = unpack_response_list(payload)
                self._seed_cache(responses)
                if self._replay_observer is not None:
                    self._replay_observer.on_responses(
                        "rs", [(r, ()) for r in responses])
                self._deliver(responses)
                continue
            # frame-parity: an unknown kind used to fall through into
            # unpack_response_list, where a garbage payload killed the
            # recv loop with a struct.error.  Log and drop instead —
            # the stream cursor already counted it, so resume replay
            # stays aligned with the coordinator's out-log.
            logger.warning("rank %d: ignoring unknown downlink frame "
                           "kind %r (%d bytes)", self.rank, magic,
                           len(payload))

    def _send_frame_counted_locked(self, magic: bytes, payload: bytes,
                                   stat_key: str, kind: str):
        """One uplink frame + its stats-dict and registry accounting in
        lockstep (caller holds self._send_lock) — the single place the
        frame-header byte math lives on the send side."""
        # Failpoint site: worker uplink.  drop() swallows the RQ/CH
        # frame before the socket — the coordinator never learns this
        # rank is ready, so the tensor must surface through rank-0
        # stall attribution, not a hang.
        if _fp.ENABLED and \
                _fp.maybe_fail("worker.frame_send",
                               rank=self.rank) == "drop":
            return
        if self._selfheal is not None:
            self._uplink_send_selfheal(magic, payload)
        else:
            _send_frame(self._sock, magic, payload)
        self.stats[stat_key] = self.stats.get(stat_key, 0) + 1
        self.stats["bytes_sent"] += len(payload) + 6
        _FRAMES_SENT.inc(1, kind=kind)
        _BYTES_SENT.inc(len(payload) + 6)
        if _fr.ENABLED:
            _fr.record(_fr.FRAME_TX, rank=self.rank, role="worker",
                       frame=kind, nbytes=len(payload) + 6,
                       seq=self._up_count if magic not in _OOS_UP
                       else None, sess=self._session_id[:8])

    def _uplink_send_selfheal(self, magic: bytes, payload: bytes):
        """Uplink send with the self-healing channel on: stamp the
        heartbeat-suppression clock, log the frame for resume replay,
        and — with reconnects enabled — absorb a dead-socket send (the
        frame is in the up-log; the handshake replays it, so a
        transient drop is invisible to the submitting thread)."""
        self._last_uplink_t = time.monotonic()
        if self._grace_s > 0 and magic not in _OOS_UP:
            self._up_count += 1
            self._up_log.append((self._up_count, magic, payload))
            try:
                _send_frame(self._sock, magic, payload)
            except OSError:
                logger.debug("uplink send hit a dead socket; frame "
                             "queued for resume replay")
        else:
            # Out-of-stream (HB/MR) frames are never logged/replayed:
            # a lost heartbeat is re-sent next interval, a lost
            # snapshot is re-covered by the next poll.
            _send_frame(self._sock, magic, payload)

    def _spawn_metrics_reply(self):
        """MR replies ride their own short-lived thread: the recv
        thread must NEVER block on _send_lock — a recv thread waiting
        on a send while both TCP buffers are full closes a distributed
        deadlock cycle with the coordinator's broadcast lock (coord
        holds its lock writing to us, our submit thread holds
        _send_lock writing to the coord, the coord's rank loop waits
        on its lock, we'd wait here).  At most one reply in flight; a
        poll arriving while the previous reply is still blocked is
        dropped — snapshots are absolute, the next poll re-covers it.
        The flag is advisory (set here, cleared by the reply thread):
        the worst race outcome is one dropped poll."""
        if self._mr_sending:
            return
        self._mr_sending = True

        def run():
            try:
                self._send_metrics_snapshot()
            finally:
                self._mr_sending = False

        threading.Thread(target=run, name="hvd-metrics-reply",
                         daemon=True).start()

    def _send_metrics_snapshot(self):
        """MQ poll answer: ship this process's registry snapshot to
        the coordinator."""
        if _sg.ENABLED and self._phase_collector is not None:
            # Fold this rank's phase EWMAs into its rank-labeled
            # gauges so THIS reply carries them: the per-rank
            # summaries ride the existing MR frame (and survive relay
            # MA pre-aggregation, because each rank only writes its
            # own label) — zero new wire kinds, zero extra frames,
            # and attribution keeps working during replay.
            self._phase_collector.publish(self.rank)
        if _prof.ENABLED:
            # Same contract for the sampling profiler's top-K hot
            # frame digest (common/profiler.py): rank-labeled gauges
            # on the existing MR frame, so rank 0 can name the frame
            # a slow rank is stuck in without any new wire kind.
            _prof.publish_digest(self.rank)
        if _slo.ENABLED:
            # And the SLO plane's windowed SLIs + burn rates
            # (common/slo.py).
            _slo.publish(self.rank)
        try:
            payload = json.dumps(metrics.snapshot()).encode()
        except (TypeError, ValueError):
            logger.warning("metrics snapshot not serializable",
                           exc_info=True)
            return
        try:
            with self._send_lock:
                self._send_frame_counted_locked(
                    _MAGIC_METRICS_REP, payload, "mr_frames", "MR")
        except OSError:
            pass  # connection teardown races the poll; never fatal

    def _deliver(self, responses: List[Response]):
        if self._on_response is not None:
            for resp in responses:
                self._on_response(resp)
            return
        self._recv_buf.put(responses)
        if self._on_receive is not None:
            self._on_receive()

    def _seed_cache(self, responses: List[Response]):
        """Store per-tensor slices of newly negotiated responses under
        the coordinator-assigned bits.  Entries for tensors this rank
        never submitted (process-set non-members, joined ranks) carry no
        signature: they resolve CB bits but never produce hits."""
        if not self.cache.enabled:
            return
        for resp in responses:
            if resp.response_type not in CACHEABLE or not resp.cache_bits:
                self._seed_log.append(
                    ("skip", resp.tensor_names, resp.process_set_id,
                     list(resp.cache_bits or ())))
                continue
            parts = split_response(resp, self.size)
            for i, name in enumerate(resp.tensor_names):
                bit = resp.cache_bits[i] if i < len(resp.cache_bits) else -1
                if bit < 0:
                    self._seed_log.append(("nobit", name,
                                           resp.process_set_id))
                    continue
                key = (resp.process_set_id, name)
                self._seed_log.append(("seed", bit, key))
                self.cache.insert(key, bit, parts[i],
                                  self._sent_sigs.get(key))

    def _reconstruct_cached(self, batches: List[List[int]]
                            ) -> Optional[List[Response]]:
        """CB frame: rebuild the fused responses from the local cache.
        By protocol a CB batch only fires when every member rank
        contributed via bit, which implies every rank (member or not)
        still holds the entries — an unknown bit is a hard desync."""
        responses = []
        for batch in batches:
            parts = [self.cache.response_for_bit(b) for b in batch]
            if any(p is None for p in parts):
                from .exceptions import HorovodInternalError
                missing = [b for b, p in zip(batch, parts) if p is None]
                self._set_broken(HorovodInternalError(
                    "response-cache desync: coordinator referenced "
                    "cache bit(s) %s this rank does not hold (batch "
                    "%s; held: %s; frames: %s; seeds: %s)" % (
                        missing, batch, self.cache.debug_bits(),
                        {k: v for k, v in self.stats.items()
                         if k.endswith("_frames")},
                        list(self._seed_log)[-12:])))
                return None
            responses.append(merge_responses(parts))
        return responses

    def try_inline_cache_hit(self, request) -> bool:
        """Submitting-thread fast path (reference cycle analog:
        operations.cc:587-645 cache-hit short circuit): on a
        response-cache hit, the caller thread sends the CH frame
        itself and returns — the background thread never wakes for
        this op, and with direct dispatch the response executes on the
        recv thread, so a steady-state eager op costs ONE context
        switch (recv -> waiting caller) instead of four.  Returns
        False on a miss (caller falls back to the negotiation queue).
        """
        if self._broken_err is not None:
            raise self._broken_err
        if not self.cache.enabled:
            return False
        # count_miss=False: a missed request falls back to the cycle,
        # whose own lookup counts the same logical miss.
        bit = self.cache.lookup_bit(request, count_miss=False)
        if bit is None:
            _INLINE.inc(1, result="miss")
            return False
        _INLINE.inc(1, result="hit")
        try:
            with self._send_lock:
                self._send_frame_counted_locked(
                    _MAGIC_HITS, pack_bits([bit]), "ch_frames", "CH")
        except OSError as e:
            from .exceptions import HorovodInternalError
            raise HorovodInternalError(
                f"could not reach the coordinator: {e}") from e
        return True

    def compute_response_list(self, pending, entry_sizes, threshold_bytes):
        if self._broken_err is not None:
            raise self._broken_err
        if pending:
            hit_bits: List[int] = []
            full: List[Request] = []
            # Group atomicity: a grouped submission travels in ONE
            # frame per rank (runtime.submit_group + pop_pending), so
            # demoting the WHOLE group to full requests whenever any
            # member misses the cache keeps all members' completion
            # counts in lockstep on the coordinator — members can
            # never finish in different rounds (one in a CB batch,
            # another in a later RS frame).
            lookups = [self.cache.lookup_bit(req)
                       if self.cache.enabled else None
                       for req in pending]
            demoted_gids = {req.group_id
                            for req, bit in zip(pending, lookups)
                            if bit is None and req.group_id >= 0}
            for req, bit in zip(pending, lookups):
                if bit is not None and (req.group_id < 0 or
                                        req.group_id not in demoted_gids):
                    hit_bits.append(bit)
                else:
                    full.append(req)
                    self._sent_sigs[(req.process_set_id,
                                     req.tensor_name)] = \
                        request_signature(req)
            try:
                with self._send_lock:
                    if hit_bits:
                        _UPLINK_BATCH.observe(len(hit_bits), kind="CH")
                        self._send_frame_counted_locked(
                            _MAGIC_HITS, pack_bits(hit_bits),
                            "ch_frames", "CH")
                    if full:
                        _UPLINK_BATCH.observe(len(full), kind="RQ")
                        self._send_frame_counted_locked(
                            _MAGIC_REQ, pack_request_list(full),
                            "rq_frames", "RQ")
            except OSError as e:
                from .exceptions import HorovodInternalError
                raise HorovodInternalError(
                    f"could not reach the coordinator: {e}") from e
        if self._pending_params is not None:
            # Everything returned before the PA marker has executed by
            # now (the runtime performs responses before calling back).
            self._apply_params(self._pending_params)
            self._pending_params = None
        responses: List[Response] = []
        try:
            # Non-blocking drain: the recv thread wakes the runtime's
            # cycle event on arrival (set_receive_callback), so there
            # is no poll-interval latency floor here.
            item = self._recv_buf.get_nowait()
            while True:
                if isinstance(item, tuple) and item[0] == "PA":
                    if responses:
                        # Batches before the marker must execute first.
                        self._pending_params = item[1]
                        break
                    self._apply_params(item[1])
                else:
                    responses.extend(item)
                item = self._recv_buf.get_nowait()
        except queue.Empty:
            pass
        return responses, []

    def set_params_hook(self, fn):
        """Runtime callback for tuned worker knobs: called with every
        decoded PA payload, at the frame's in-stream position (see
        _apply_params)."""
        self._params_hook = fn

    def _apply_params(self, params: dict):
        """Adopt autotuned parameters announced by the coordinator
        (reference: Controller::SynchronizeParameters)."""
        if "hierarchical" in params:
            self.state.knobs.hierarchical_allreduce = \
                bool(params["hierarchical"])
        if self._params_hook is not None:
            # Tuned worker knobs (cycle time, coalescing, replay
            # warmup) + the tuning_active lifecycle bit that holds or
            # releases steady-state replay.
            self._params_hook(params)

    def shutdown(self):
        self._closing = True
        self._hb_stop.set()
        try:
            with self._send_lock:
                _send_frame(self._sock, _MAGIC_REQ,
                            pack_request_list([], shutdown=True))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self.server is not None:
            self._drain_server()
            self.server.stop()
        # Hosted relays stop LAST: peer ranks' shutdown frames may
        # still be riding them while the coordinator drains.
        for rs in self._hosted_relays:
            try:
                rs.shutdown()
            except Exception:
                logger.warning("relay shutdown failed", exc_info=True)
        self._hosted_relays = []

    # Grace window: if the set of ever-connected ranks is stagnant and
    # all of them departed, remaining ranks crashed before connecting —
    # no point waiting out the full timeout.
    _DRAIN_STAGNATION_S = 5.0

    def _drain_server(self):
        """Keep serving until every rank departed, so ranks still
        initializing (or draining) can reach the coordinator (the
        reference's background thread likewise serves until all ranks
        shut down, operations.cc:539-585).  Elastic resets use a short
        cap: peers fail over via the broken-membership path anyway."""
        timeout = 5.0 if self.state.knobs.elastic else \
            env_mod.start_timeout()
        deadline = time.monotonic() + timeout
        prev_seen = -1
        stagnant_since = time.monotonic()
        while time.monotonic() < deadline:
            seen, departed = self.server.departure_counts()
            if departed >= self.size:
                return
            now = time.monotonic()
            if seen != prev_seen:
                prev_seen = seen
                stagnant_since = now
            elif departed >= seen and \
                    now - stagnant_since > self._DRAIN_STAGNATION_S:
                logger.warning(
                    "stopping coordinator: %d/%d ranks never "
                    "connected", self.size - seen, self.size)
                return
            time.sleep(0.1)
        logger.warning("stopping coordinator with ranks still attached "
                       "(waited %.0fs)", timeout)
