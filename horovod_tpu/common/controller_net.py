"""Multi-process controller: coordinator/worker negotiation over TCP.

The TPU port of the reference's coordinator protocol (reference:
controller.h:69-102 protocol spec; mpi_controller.cc / gloo_controller.cc
transport implementations): every rank pushes its ready Requests to the
rank-0 coordinator; the coordinator counts readiness per tensor
(IncrementTensorCount), validates and constructs fused Responses, and
broadcasts one ordered ResponseList to every rank.  Each rank then
executes the identical fused batch — which on the XLA data plane means
every process enters the same compiled collective program (order
determinism is what makes the executable cache effective, SURVEY §7).

Deltas from the reference:
  * event-driven push instead of a 1 ms gather cycle — ranks send only
    when they have pending work, the coordinator fires a response batch
    as soon as every rank has reported a tensor (lower latency than
    cycle polling, no idle chatter over DCN);
  * transport is plain length-prefixed TCP (no MPI/gloo dependency) —
    the launcher provides HOROVOD_CONTROLLER_ADDR.
"""

import json
import logging
import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from . import failpoints as _fp
from . import metrics
from .controller import Controller, MessageTable, construct_response
from .fusion import fuse_responses
from .message import (Request, RequestType, Response, ResponseType,
                      dtype_size, pack_bit_batches, pack_bits,
                      pack_request_list, pack_response_list,
                      unpack_bit_batches, unpack_bits,
                      unpack_request_list, unpack_response_list)
from .response_cache import (CACHEABLE, CoordinatorCache,
                             WorkerResponseCache, merge_responses,
                             request_signature, signature_to_request,
                             split_response)

logger = logging.getLogger("horovod_tpu.controller_net")

CONTROLLER_ADDR_ENV = "HOROVOD_CONTROLLER_ADDR"

_MAGIC_REQ = b"RQ"      # worker→coord: full request list
_MAGIC_RESP = b"RS"     # coord→worker: full response list
_MAGIC_HITS = b"CH"     # worker→coord: cache-hit bit list (fast path)
_MAGIC_CACHE = b"CB"    # coord→worker: fused batches of cache bits
_MAGIC_EVICT = b"EV"    # coord→worker: evicted cache bits
_MAGIC_PARAMS = b"PA"   # coord→worker: autotuned runtime parameters
_MAGIC_ABORT = b"AB"    # coord→worker: membership broken, fail fast
_MAGIC_METRICS_REQ = b"MQ"  # coord→worker: send a metrics snapshot
_MAGIC_METRICS_REP = b"MR"  # worker→coord: metrics snapshot (JSON)

_FRAMES_SENT = metrics.counter(
    "hvd_frames_sent_total", "Control-plane frames sent, by kind")
_FRAMES_RECV = metrics.counter(
    "hvd_frames_recv_total", "Control-plane frames received, by kind")
_BYTES_SENT = metrics.counter(
    "hvd_bytes_sent_total", "Control-plane bytes sent (incl. headers)")
_BYTES_RECV = metrics.counter(
    "hvd_bytes_recv_total",
    "Control-plane bytes received (incl. headers)")
_INLINE = metrics.counter(
    "hvd_inline_cache_total",
    "Submitting-thread inline fast-path outcomes (hit = CH frame sent "
    "without waking the background thread)")
_ROUNDS = metrics.counter(
    "hvd_negotiation_rounds_total",
    "Coordinator broadcast rounds, by kind (fast = pure cache-bit CB "
    "frame, full = negotiated RS frame)")
_COORD_TENSORS = metrics.counter(
    "hvd_negotiated_tensors_total",
    "Tensors completed on the coordinator, by path")
_UPLINK_BATCH = metrics.histogram(
    "hvd_uplink_requests_per_frame",
    "Requests/bits coalesced into one uplink frame, by kind (drain-"
    "all-pending coalescing: frame count tracks batch count, not "
    "tensor count)", bounds=metrics.COUNT_BUCKETS)


def _send_frame(sock: socket.socket, magic: bytes, payload: bytes):
    sock.sendall(magic + struct.pack("<I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[Tuple[bytes, bytes]]:
    head = _recv_exact(sock, 6)
    if head is None:
        return None
    magic, ln = head[:2], struct.unpack("<I", head[2:])[0]
    payload = _recv_exact(sock, ln)
    if payload is None:
        return None
    return magic, payload


class CoordinatorServer:
    """Rank-0 service: accepts one connection per rank (including a
    loopback connection from rank 0's own worker), matches requests,
    broadcasts fused response lists."""

    def __init__(self, size: int, bind_addr: str = "0.0.0.0",
                 port: int = 0, fusion_threshold: int = 64 << 20,
                 timeline=None, elastic: bool = False,
                 allow_ephemeral_fallback: bool = False,
                 param_manager=None, cache_capacity: int = 1024,
                 stall_warning_time_s: float = 60.0,
                 stall_shutdown_time_s: float = 0.0,
                 metrics_interval_s: float = 0.0):
        self.size = size
        self.fusion_threshold = fusion_threshold
        self.timeline = timeline
        self.elastic = elastic
        self.allow_ephemeral_fallback = allow_ephemeral_fallback
        self._broken = False
        # Autotuner (rank-0 only: fusion planning happens here, so the
        # threshold needs no cross-rank sync — reference
        # parameter_manager.cc semantics, SURVEY §2.1).
        self.param_manager = param_manager
        if param_manager is not None:
            param_manager.fusion_threshold_bytes = fusion_threshold
        # Last PA-frame-synced categorical params version (-1 = stock
        # configuration, nothing announced yet).
        self._synced_params_version = -1
        self._synced_params = None
        self._table = MessageTable()
        self._seen = 0
        self._departed = 0
        self._departed_cond = threading.Condition()
        # (psid, name) -> element count, for fusion byte accounting
        self._elem_cache: Dict[tuple, int] = {}
        # (psid, name) -> grouped-submission id (group-atomic fusion)
        self._group_ids: Dict[tuple, int] = {}
        self._joined: Set[int] = set()
        self._last_joined = -1
        # barrier (psid, name) -> ranks arrived
        self._barriers: Dict[tuple, Set[int]] = {}
        # barrier (psid, name) -> member ranks (for stall attribution)
        self._barrier_members: Dict[tuple, Tuple[int, ...]] = {}
        # --- response-cache fast path (reference controller.cc:81-236) ---
        self._cache = CoordinatorCache(cache_capacity)
        # (psid, name) -> True while every contribution this round came
        # from a live cache bit (a full request degrades the round)
        self._bit_only: Dict[tuple, bool] = {}
        self._pending_evictions: List[int] = []
        self.stats = {"full_rounds": 0, "fast_rounds": 0,
                      "fast_tensors": 0, "negotiated_tensors": 0}
        # --- coordinator-side stall attribution (reference
        #     stall_inspector.h:74-80: rank 0 names which ranks are
        #     missing a tensor) ---
        self._first_seen: Dict[tuple, float] = {}
        self._stall_warning_s = stall_warning_time_s
        self._stall_shutdown_s = stall_shutdown_time_s
        self._stall_logged: Dict[tuple, float] = {}
        self._conns: Dict[int, socket.socket] = {}
        # Formation gate: NOTHING may be negotiated (and so no frame
        # broadcast) until every rank of this incarnation has
        # connected — a response completed among early connectors
        # would never reach a late one (measured: subgroup-first
        # traffic wedged/desynced ranks that missed the first RS,
        # tests/test_stress_protocol.py).  Uplink frames arriving
        # before formation buffer here and drain, in arrival order,
        # when the last rank registers.
        self._formed = size <= 1
        self._pre_formed: List[tuple] = []  # (kind, rank, payload)
        self._started_at = time.monotonic()  # formation-stall clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._srv.bind((bind_addr, port))
        except OSError:
            if not self.allow_ephemeral_fallback:
                # Without a rendezvous store to publish the real port,
                # an ephemeral fallback would leave workers hanging on
                # the dead env-contract port — fail crisply instead.
                raise
            # The launcher-chosen port got taken in the meantime; fall
            # back to an ephemeral port.  The actual address is
            # published through the rendezvous KV store, which workers
            # prefer over the env contract.
            logger.warning("controller port %d unavailable; using an "
                           "ephemeral port", port)
            self._srv.bind((bind_addr, 0))
        self._srv.listen(size + 4)
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="hvd-coord-accept", daemon=True)
        self._threads: List[threading.Thread] = []
        self._accept_thread.start()
        self._stall_thread = None
        if stall_warning_time_s > 0:
            self._stall_thread = threading.Thread(
                target=self._stall_loop, name="hvd-coord-stall",
                daemon=True)
            self._stall_thread.start()
        # --- cross-rank metrics aggregation (MQ/MR frames): collect
        #     per-rank registry snapshots and expose the merged view,
        #     the metrics analog of the rank-0 stall report ---
        self._rank_metrics: Dict[int, dict] = {}
        self._metrics_interval_s = metrics_interval_s
        self._metrics_thread = None
        if metrics_interval_s > 0:
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="hvd-coord-metrics",
                daemon=True)
            self._metrics_thread.start()

    def _accept_loop(self):
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # First frame identifies the rank.  Bound the wait so a
            # connected-but-silent client can't stall registration of
            # the remaining ranks.
            conn.settimeout(30.0)
            try:
                frame = _recv_frame(conn)
            except (socket.timeout, OSError):
                conn.close()
                continue
            conn.settimeout(None)
            if frame is None:
                conn.close()
                continue
            rank = struct.unpack("<i", frame[1])[0]
            with self._lock:
                self._conns[rank] = conn
                # Late joiners (elastic re-rendezvous) must start from
                # the currently announced parameters, and they see the
                # PA frame before any response frame — the same stream
                # position every other worker saw it at.
                if self._synced_params is not None:
                    try:
                        _send_frame(conn, _MAGIC_PARAMS,
                                    self._synced_params)
                    except OSError:
                        pass
                if not self._formed and len(self._conns) >= self.size:
                    self._formed = True
                    pre, self._pre_formed = self._pre_formed, []
                    for kind, r, payload in pre:
                        self._dispatch_uplink_locked(kind, r, payload)
            with self._departed_cond:
                self._seen += 1
                self._departed_cond.notify_all()
            t = threading.Thread(target=self._rank_loop, args=(rank, conn),
                                 name=f"hvd-coord-rank{rank}", daemon=True)
            t.start()
            self._threads.append(t)

    def _rank_loop(self, rank: int, conn: socket.socket):
        clean = False
        try:
            while not self._stop.is_set():
                try:
                    frame = _recv_frame(conn)
                except OSError:
                    frame = None
                if frame is None:
                    return
                magic, payload = frame
                # Failpoint site: uplink frame arrival on the
                # coordinator.  drop() discards the frame (the sender's
                # tensor goes incomplete — the stall machinery must
                # attribute and fail it); error() kills this rank loop,
                # which the coordinator treats as the rank departing.
                if _fp.ENABLED and \
                        _fp.maybe_fail("coord.frame_recv",
                                       rank=rank) == "drop":
                    continue
                _FRAMES_RECV.inc(1, kind=magic.decode("ascii",
                                                      "replace"))
                _BYTES_RECV.inc(len(payload) + 6)
                if magic == _MAGIC_HITS:
                    self._handle_cache_hits(rank, unpack_bits(payload))
                    continue
                if magic == _MAGIC_METRICS_REP:
                    self._handle_metrics_snapshot(rank, payload)
                    continue
                requests, shutdown = unpack_request_list(payload)
                if shutdown:
                    clean = True
                    return
                self._handle_requests(rank, requests)
        finally:
            with self._departed_cond:
                self._departed += 1
                self._departed_cond.notify_all()
            if not self._stop.is_set():
                self._on_rank_lost(rank, clean)

    def departure_counts(self):
        """(ever_connected, departed) rank-connection counters."""
        with self._departed_cond:
            return self._seen, self._departed

    # ------------------------------------------------------------------
    # cross-rank metrics aggregation
    # ------------------------------------------------------------------
    def _metrics_loop(self):
        while not self._stop.wait(self._metrics_interval_s):
            self.request_metrics()

    def request_metrics(self):
        """Broadcast one MQ poll; every worker (including rank 0's
        loopback client) answers with an MR snapshot frame."""
        with self._lock:
            self._broadcast_frame_locked(_MAGIC_METRICS_REQ, b"")

    def _handle_metrics_snapshot(self, rank: int, payload: bytes):
        try:
            snap = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            logger.warning("undecodable metrics snapshot from rank %d",
                           rank)
            return
        with self._lock:
            self._rank_metrics[rank] = snap

    def merged_metrics(self) -> Optional[dict]:
        """Sum of the latest per-rank snapshots (None until the first
        MR frame lands).  ``ranks`` names the contributors, so a
        scraper can tell a partial merge from a full one."""
        with self._lock:
            snaps = dict(self._rank_metrics)
        if not snaps:
            return None
        merged = metrics.merge_snapshots(snaps[r] for r in sorted(snaps))
        merged["ranks"] = sorted(snaps)
        return merged

    def _on_rank_lost(self, rank: int, clean: bool):
        """A rank departed mid-run.  In elastic mode, pending
        negotiations can never complete: fail them on every surviving
        rank so blocked synchronize() calls raise HorovodInternalError
        and unwind to the elastic retry loop (the analog of the
        reference's collective errors on peer failure,
        common/exceptions.py:18 semantics)."""
        with self._lock:
            # A departed rank must stop contributing to the merged
            # metrics view: its frozen last snapshot would otherwise be
            # summed into every future merge, and the ``ranks``
            # contributor list would keep advertising a dead process.
            self._rank_metrics.pop(rank, None)
        if not self.elastic:
            return
        with self._lock:
            self._conns.pop(rank, None)
            self._broken = True
            # Keys are (psid, name); the ERROR responses must carry
            # BOTH — workers pop their tensor-table entries by
            # (name, psid), so an error missing the psid never reaches
            # a non-global set's blocked submitter.  Pre-formation
            # buffered requests fail too: their submitters are blocked
            # just the same.
            pending = list(self._table.entries.keys()) + \
                list(self._barriers.keys()) + \
                [(req.process_set_id, req.tensor_name)
                 for kind, _, payload in self._pre_formed
                 if kind == "rq" for req in payload]
            self._pre_formed.clear()
            self._table.entries.clear()
            self._barriers.clear()
            self._barrier_members.clear()
            self._first_seen.clear()
            self._bit_only.clear()
            msg = (f"rank {rank} left the job "
                   f"({'clean' if clean else 'connection lost'}); "
                   "membership changed")
            logger.info("elastic coordinator: %s", msg)
            responses = [Response(
                response_type=ResponseType.ERROR, tensor_names=[name],
                process_set_id=psid,
                error_message=msg) for psid, name in pending]
            if responses:
                self._broadcast_locked(responses)
            # Abort broadcast: a worker with NO pending eager
            # negotiation (e.g. blocked inside a TF in-graph
            # collective, or compute-bound) must still learn the
            # membership broke NOW — while this coordinator is alive —
            # so it can unwind and disconnect its jax client before
            # rank 0 takes the coordination service down (leader loss
            # under an attached client is process-fatal).
            self._broadcast_frame_locked(_MAGIC_ABORT, msg.encode())

    def _broadcast_locked(self, responses: List[Response]):
        self._broadcast_frame_locked(_MAGIC_RESP,
                                     pack_response_list(responses))

    @staticmethod
    def _required_for(req: Request) -> int:
        return len(req.process_set_ranks) if req.process_set_ranks else 0

    def _joined_count_for(self, req: Request) -> int:
        if req.process_set_ranks:
            return len(self._joined & set(req.process_set_ranks))
        return len(self._joined)

    def _scan_complete(self) -> List[Tuple[str, List[Request]]]:
        """Re-scan the message table for tensors completed by a rank
        joining (the reference fires pending tensors when join
        participation changes, controller.cc:254-308)."""
        ready: List[Tuple[tuple, List[Request]]] = []
        for key in list(self._table.entries.keys()):
            msgs = self._table.entries[key]
            if not msgs:
                continue
            required = self._required_for(msgs[0]) or self.size
            if len(msgs) + self._joined_count_for(msgs[0]) >= required:
                self._table.pop(key)
                self._first_seen.pop(key, None)
                ready.append((key, msgs))
        return ready

    def _handle_requests(self, rank: int, requests: List[Request]):
        with self._lock:
            # _broken outranks the formation gate: after an elastic
            # rank loss during formation the gate can never open, and
            # buffering would hide the failure from the submitter
            # forever — _process's broken branch errors it instead.
            if not self._formed and not self._broken:
                self._pre_formed.append(("rq", rank, requests))
                return
            self._dispatch_uplink_locked("rq", rank, requests)

    def _handle_cache_hits(self, rank: int, bits: List[int]):
        """Fast-path uplink: each bit is a full request the worker
        elided because its cached signature still matches (reference:
        CacheCoordinator::sync)."""
        with self._lock:
            if not self._formed and not self._broken:
                # Unreachable with a fresh cache (no bit precedes the
                # first RS, which the gate itself blocks) — buffered
                # for defense in depth.
                self._pre_formed.append(("ch", rank, bits))
                return
            self._dispatch_uplink_locked("ch", rank, bits)

    def _dispatch_uplink_locked(self, kind: str, rank: int, payload):
        """Route one uplink frame ("rq" request list / "ch" bit list)
        into _process; shared by the live path and the formation-gate
        drain (caller holds self._lock)."""
        if kind == "rq":
            items = [(req, False) for req in payload]
        else:
            items = self._resolve_hits(rank, payload)
        if items:
            self._process(rank, items)

    def _resolve_hits(self, rank: int, bits: List[int]
                      ) -> List[Tuple[Request, bool]]:
        """Resolve CH bits into requests (caller holds self._lock)."""
        items: List[Tuple[Request, bool]] = []
        for bit in bits:
            resolved = self._cache.resolve_bit(bit)
            if resolved is None:
                # Only possible if >TOMBSTONE_CAP evictions raced one
                # in-flight frame — effectively unreachable; the
                # sender's tensor would hang, so fail loudly.
                logger.error(
                    "unresolvable cache bit %d from rank %d; "
                    "protocol desync", bit, rank)
                self._broadcast_locked([Response(
                    response_type=ResponseType.ERROR,
                    tensor_names=[f"__cache_bit_{bit}"],
                    error_message="response-cache protocol desync")])
                continue
            live, key, sig, sizes, gid = resolved
            name = key[1]  # cache keys are (psid, name)
            first_dim = None
            if sig[7] == int(RequestType.ALLGATHER) and sizes:
                # tensor_sizes are in GROUP order: index by the
                # rank's position in the process set when one is
                # given; a rank outside the set gets NO override
                # (mirrors the native coordinator).
                psr = sig[8]
                if psr:
                    idx = psr.index(rank) if rank in psr else -1
                else:
                    idx = rank
                if 0 <= idx < len(sizes):
                    first_dim = sizes[idx]
            req = signature_to_request(sig, rank, name, first_dim)
            req.group_id = gid
            # A tombstoned bit still counts as a contribution, but
            # forces the full (renegotiation) path.
            items.append((req, live))
        return items

    def _process(self, rank: int, items: List[Tuple[Request, bool]]):
        """Accumulate; fire fused broadcasts with everything that became
        ready (single-threaded per coordinator via the lock: ordering of
        broadcast frames is the global execution order).  Caller holds
        self._lock."""
        if self._broken:
            # Membership already changed this epoch: every new
            # request fails fast so submitters unwind promptly.
            self._broadcast_locked([Response(
                response_type=ResponseType.ERROR,
                tensor_names=[req.tensor_name],
                process_set_id=req.process_set_id,
                error_message="membership changed; collective "
                              "cannot complete")
                for req, _ in items])
            return
        # Every per-tensor dict below is keyed by (process_set_id,
        # name): the same name may be live on two process sets at once
        # (reference analog: per-set controllers in process_set.h).
        ready: List[Tuple[tuple, Optional[List[Request]], Optional[Response]]] = []
        for req, from_cache in items:
            name = req.tensor_name
            key = MessageTable.key(req)
            n = 1
            for d in req.tensor_shape:
                n *= d
            self._elem_cache[key] = n
            self._group_ids[key] = req.group_id
            if req.request_type == RequestType.JOIN:
                self._joined.add(rank)
                self._last_joined = rank
                if len(self._joined) == self.size:
                    ready.append((key, None, Response(
                        response_type=ResponseType.JOIN,
                        tensor_names=["join"],
                        last_joined_rank=self._last_joined)))
                    self._joined.clear()
                else:
                    # Tensors waiting only on the joined rank are
                    # now complete (zeros substituted).  Force the
                    # full-negotiation path: a cached response would
                    # carry the joined rank's old contribution (e.g.
                    # nonzero allgather row counts) whereas
                    # construct_response records zeros for it.
                    for ckey, msgs in self._scan_complete():
                        self._bit_only[ckey] = False
                        ready.append((ckey, msgs, None))
                continue
            if req.request_type == RequestType.BARRIER:
                required = self._required_for(req) or self.size
                arrived = self._barriers.setdefault(key, set())
                arrived.add(rank)
                # Barriers live outside the message table, so they need
                # their own stall clock: a rank dying at a barrier must
                # surface through attribution + shutdown like any other
                # collective, not hang the arrived ranks forever.
                self._first_seen.setdefault(key, time.monotonic())
                self._barrier_members[key] = req.process_set_ranks
                if len(arrived) >= required:
                    del self._barriers[key]
                    self._barrier_members.pop(key, None)
                    self._first_seen.pop(key, None)
                    ready.append((key, None, Response(
                        response_type=ResponseType.BARRIER,
                        tensor_names=[name],
                        process_set_id=req.process_set_id,
                        process_set_ranks=req.process_set_ranks)))
                continue
            if not from_cache:
                self._bit_only[key] = False
                if self._cache.has(key):
                    # Signature changed on some rank (or it evicted
                    # locally): renegotiate from scratch so the cached
                    # response can never serve a stale shape/dtype
                    # (reference: INVALID → eviction,
                    # response_cache.cc:49-87).
                    bit = self._cache.evict_name(key)
                    if bit is not None:
                        self._pending_evictions.append(bit)
            else:
                self._bit_only.setdefault(key, True)
            required = self._required_for(req) or self.size
            self._first_seen.setdefault(key, time.monotonic())
            complete = self._table.increment(
                req, required,
                joined_count=self._joined_count_for(req))
            if self.timeline:
                self.timeline.negotiate_rank_ready(name, rank)
            if complete:
                msgs = self._table.pop(key)
                self._first_seen.pop(key, None)
                ready.append((key, msgs, None))
        if not ready:
            self._flush_evictions_locked()
            return

        # Partition completed tensors: pure-bit rounds ride the compact
        # CB frame; anything else is (re)negotiated and re-cached.  A
        # grouped submission must not straddle the two frames (group
        # atomicity): if any member renegotiates, every member of that
        # group is demoted to the full path this round.
        full_groups: Set[int] = set()
        for key, msgs, direct in ready:
            if direct is None and not (
                    self._bit_only.get(key, False) and
                    self._cache.has(key)):
                gid = self._group_ids.get(key, -1)
                if gid >= 0:
                    full_groups.add(gid)
        hit_responses: List[Response] = []
        full_responses: List[Response] = []
        sig_by_key: Dict[tuple, tuple] = {}
        for key, msgs, direct in ready:
            if direct is not None:
                full_responses.append(direct)
                continue
            bit_only = self._bit_only.pop(key, False)
            self._stall_logged.pop(key, None)
            ent = self._cache.get(key)
            # While any rank is joined, cached responses are stale for
            # it (renegotiation substitutes zeros for joined ranks) —
            # bypass the fast path entirely.
            if bit_only and ent is not None and not self._joined and \
                    self._group_ids.get(key, -1) not in full_groups:
                hit_responses.append(ent[1])
                self.stats["fast_tensors"] += 1
                _COORD_TENSORS.inc(1, path="fast")
                continue
            resp = construct_response(msgs[0].tensor_name, msgs,
                                      self.size, self._joined)
            sig_by_key[key] = request_signature(msgs[0])
            full_responses.append(resp)
            self.stats["negotiated_tensors"] += 1
            _COORD_TENSORS.inc(1, path="negotiated")
            self._cache.clear_tombstones_for(key)

        nbytes = 0
        if hit_responses:
            fused_hits = fuse_responses(
                hit_responses, self._elem_cache, self.fusion_threshold,
                self._group_ids)
            batches = [[self._cache.get((fr.process_set_id, n))[0]
                        for n in fr.tensor_names]
                       for fr in fused_hits]
            payload = pack_bit_batches(batches)
            self._broadcast_frame_locked(_MAGIC_CACHE, payload)
            self.stats["fast_rounds"] += 1
            _ROUNDS.inc(1, kind="fast")
            nbytes += sum(self._elem_cache.get((fr.process_set_id, n),
                                               0) *
                          dtype_size(fr.tensor_type)
                          for fr in fused_hits for n in fr.tensor_names)
        if full_responses:
            fused = fuse_responses(full_responses, self._elem_cache,
                                   self.fusion_threshold, self._group_ids)
            if self._cache.enabled:
                self._assign_cache_bits(fused, sig_by_key)
            self._flush_evictions_locked()
            self._broadcast_locked(fused)
            self.stats["full_rounds"] += 1
            _ROUNDS.inc(1, kind="full")
            nbytes += sum(self._elem_cache.get((fr.process_set_id, n),
                                               0) *
                          dtype_size(fr.tensor_type)
                          for fr in fused for n in fr.tensor_names)
        else:
            self._flush_evictions_locked()
        if self.param_manager is not None:
            if self.param_manager.active:
                self.param_manager.record_step(nbytes)
                self.fusion_threshold = \
                    self.param_manager.fusion_threshold_bytes
            if self.param_manager.params_version != \
                    self._synced_params_version:
                self._sync_tuned_params_locked()

    def _sync_tuned_params_locked(self):
        """Announce the autotuner's categorical knobs to every worker
        via a PA frame (the reference broadcasts tuned params through
        the controller, controller.cc:39-53).  Broadcast under the
        server lock positions the frame identically in every worker's
        response stream, so all ranks flip between the same two fused
        batches."""
        pm = self.param_manager
        params = pm.categorical_params
        self._synced_params_version = pm.params_version
        cache_on = bool(params["cache"])
        if cache_on != self._cache.enabled:
            self._pending_evictions.extend(
                self._cache.set_enabled(cache_on))
            self._flush_evictions_locked()
        payload = json.dumps({
            "hierarchical": bool(params["hierarchical"]),
            "cache": cache_on,
            "fusion": int(self.fusion_threshold),
        }).encode()
        self._synced_params = payload
        self._broadcast_frame_locked(_MAGIC_PARAMS, payload)

    def _assign_cache_bits(self, fused: List[Response],
                           sig_by_key: Dict[tuple, tuple]):
        """Seed the cache from freshly negotiated responses and stamp
        the coordinator-assigned bits onto the wire."""
        pending = set(self._table.entries.keys())
        for resp in fused:
            if resp.response_type not in CACHEABLE or resp.error_message:
                continue
            parts = split_response(resp, self.size)
            bits = []
            for i, name in enumerate(resp.tensor_names):
                key = (resp.process_set_id, name)
                sig = sig_by_key.get(key)
                if sig is None:
                    bits.append(-1)
                    continue
                bit, evicted = self._cache.insert(
                    key, parts[i], sig, self._group_ids.get(key, -1),
                    pending)
                bits.append(bit)
                self._pending_evictions.extend(evicted)
            resp.cache_bits = bits

    def _flush_evictions_locked(self):
        if self._pending_evictions:
            self._broadcast_frame_locked(
                _MAGIC_EVICT, pack_bits(self._pending_evictions))
            self._pending_evictions = []

    def _broadcast_frame_locked(self, magic: bytes, payload: bytes):
        # Failpoint site: coordinator broadcast fan-out.  drop()
        # suppresses one whole downlink frame — every rank misses it,
        # the negotiation wedges, and the stall shutdown must fail the
        # collective rather than hang the job.  error() degrades to
        # the same drop semantics: a raise here would propagate into
        # whichever caller holds the lock (rank loops, the stall and
        # metrics threads) and permanently kill the very machinery
        # that bounds the fault.
        if _fp.ENABLED:
            try:
                if _fp.maybe_fail("coord.broadcast") == "drop":
                    return
            except _fp.FailpointError:
                logger.warning("failpoint coord.broadcast: injected "
                               "error; dropping the frame")
                return
        dead = []
        for r, conn in self._conns.items():
            try:
                _send_frame(conn, magic, payload)
            except OSError:
                dead.append(r)
        for r in dead:
            self._conns.pop(r, None)
        sent = len(self._conns)
        if sent:
            # Coordinator fan-out is the dominant control-plane send
            # volume on rank 0 — account it next to the worker-side
            # counters (same registry, same process).
            _FRAMES_SENT.inc(sent, kind=magic.decode("ascii", "replace"))
            _BYTES_SENT.inc(sent * (len(payload) + 6))

    # ------------------------------------------------------------------
    # stall attribution (reference stall_inspector.{h,cc}: rank-0 names
    # which ranks submitted a tensor and which did not)
    # ------------------------------------------------------------------
    def _check_formation_stall(self):
        """Pre-formation requests never enter the message table, so
        the per-tensor stall report is blind to a rank that crashes
        before connecting — attribute THAT stall here, and past the
        shutdown threshold fail the buffered collectives (the failure
        class the stall machinery exists for)."""
        with self._lock:
            if self._formed or not self._pre_formed:
                return
            age = time.monotonic() - self._started_at
            if age < self._stall_warning_s:
                return
            missing = sorted(set(range(self.size)) -
                             set(self._conns.keys()))
            last = self._stall_logged.get(("__formation__",), 0.0)
            if age - last >= self._stall_warning_s or last == 0:
                self._stall_logged[("__formation__",)] = age
                logger.warning(
                    "STALL: waiting for ranks %s to connect for %.0fs "
                    "(%d/%d registered, %d requests buffered)",
                    missing, age, len(self._conns), self.size,
                    len(self._pre_formed))
            if 0 < self._stall_shutdown_s <= age:
                pre, self._pre_formed = self._pre_formed, []
                errs = [Response(
                    response_type=ResponseType.ERROR,
                    tensor_names=[req.tensor_name],
                    process_set_id=req.process_set_id,
                    error_message=(
                        "ranks %s never connected within %.0fs"
                        % (missing, self._stall_shutdown_s)))
                    for kind, _, payload in pre if kind == "rq"
                    for req in payload]
                if errs:
                    self._broadcast_locked(errs)

    def stall_report(self) -> List[Tuple[str, List[int], List[int], float]]:
        """(tensor, submitted_ranks, missing_ranks, age_s) for every
        tensor — including pending barriers — stuck longer than the
        warning threshold."""
        now = time.monotonic()
        out = []
        with self._lock:
            for key, msgs in self._table.entries.items():
                if not msgs:
                    continue
                ts = self._first_seen.get(key)
                if ts is None or now - ts < self._stall_warning_s:
                    continue
                submitted = sorted({m.request_rank for m in msgs})
                members = msgs[0].process_set_ranks or range(self.size)
                missing = sorted(set(members) - set(submitted)
                                 - self._joined)
                out.append((key, submitted, missing, now - ts))
            for key, arrived in self._barriers.items():
                ts = self._first_seen.get(key)
                if ts is None or now - ts < self._stall_warning_s:
                    continue
                members = self._barrier_members.get(key) or \
                    range(self.size)
                missing = sorted(set(members) - arrived - self._joined)
                out.append((key, sorted(arrived), missing, now - ts))
        return out

    def _stall_loop(self):
        interval = max(min(self._stall_warning_s / 2.0, 10.0), 0.25)
        while not self._stop.wait(interval):
            self._check_formation_stall()
            for key, submitted, missing, age in self.stall_report():
                name = key[1]
                last = self._stall_logged.get(key, 0.0)
                if age - last < self._stall_warning_s and last > 0:
                    continue
                self._stall_logged[key] = age
                logger.warning(
                    "STALL: tensor %s — ranks %s submitted, ranks %s "
                    "have not, for %.0fs. One or more ranks may be "
                    "running a different graph or have hung.",
                    name, submitted, missing, age)
                if 0 < self._stall_shutdown_s <= age:
                    logger.error(
                        "stalled tensor %s exceeded shutdown threshold "
                        "(%.0fs); failing the collective", name,
                        self._stall_shutdown_s)
                    with self._lock:
                        msgs = self._table.pop(key)
                        # Barriers stall too (tracked outside the
                        # message table); fail the arrived ranks the
                        # same way.
                        stalled_barrier = \
                            self._barriers.pop(key, None) is not None
                        self._barrier_members.pop(key, None)
                        self._first_seen.pop(key, None)
                        self._bit_only.pop(key, None)
                        if msgs or stalled_barrier:
                            self._broadcast_locked([Response(
                                response_type=ResponseType.ERROR,
                                tensor_names=[name],
                                process_set_id=key[0],
                                error_message=(
                                    f"collective {name} stalled: ranks "
                                    f"{missing} never submitted it "
                                    f"within {self._stall_shutdown_s:.0f}"
                                    "s"))])

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()


class NetworkController(Controller):
    """Per-rank controller client.  Rank 0 additionally hosts the
    CoordinatorServer (mirroring the reference where rank 0 is both a
    worker and the coordinator, controller.cc:69-449)."""

    def __init__(self, state):
        super().__init__(state)
        self.server: Optional[CoordinatorServer] = None
        self._closing = False
        self._broken_err: Optional[Exception] = None
        # Worker-side response cache (fast-path uplink/downlink); the
        # coordinator owns bit assignment, we just follow the RS frames.
        self.cache = WorkerResponseCache(state.knobs.cache_capacity)
        self._sent_sigs: Dict[tuple, tuple] = {}  # (psid, name) -> sig
        # Bounded cache-seed diagnostics (read on desync only).
        from collections import deque
        self._seed_log = deque(maxlen=64)
        self.stats = {"rq_frames": 0, "ch_frames": 0, "rs_frames": 0,
                      "cb_frames": 0, "ev_frames": 0, "pa_frames": 0,
                      "mr_frames": 0,
                      "bytes_sent": 0, "bytes_recv": 0}
        # PA params stashed until the batches received before them have
        # executed (applied at the next compute_response_list entry).
        self._pending_params: Optional[dict] = None
        # True while an MR (metrics snapshot) reply thread is in
        # flight; written only by the recv thread.
        self._mr_sending = False
        self._replay_observer = None
        addr = os.environ.get(CONTROLLER_ADDR_ENV)
        if self.rank == 0:
            port = 0
            if addr and ":" in addr:
                port = int(addr.rsplit(":", 1)[1])
            param_manager = None
            if state.knobs.autotune:
                from .parameter_manager import ParameterManager
                param_manager = ParameterManager(
                    warmup_samples=state.knobs.autotune_warmup_samples,
                    steps_per_sample=state.knobs.autotune_steps_per_sample,
                    bayes_opt_max_samples=(
                        state.knobs.autotune_bayes_opt_max_samples),
                    gp_noise=state.knobs.autotune_gaussian_process_noise,
                    initial_fusion_bytes=(
                        state.knobs.fusion_threshold_bytes),
                    initial_cycle_ms=state.knobs.cycle_time_ms,
                    # Explicit env settings pin the categorical dims.
                    fixed_hierarchical=state.knobs.hierarchical_allreduce,
                    fixed_cache=(False if state.knobs.cache_capacity == 0
                                 else None),
                    log_path=state.knobs.autotune_log)
                state.parameter_manager = param_manager
            self.server = self._make_server(state, port, param_manager)
            self._publish_actual_addr(addr, self.server.port)
            host = "127.0.0.1"
            self._addr = (host, self.server.port)
        else:
            resolved = self._resolve_addr(addr)
            if not resolved:
                raise RuntimeError(
                    f"{CONTROLLER_ADDR_ENV} must be set for multi-process "
                    "runs (the launcher sets it automatically).")
            host, port = resolved.rsplit(":", 1)
            self._addr = (host, int(port))
        self._sock = self._connect()
        self._recv_buf: "queue.Queue" = queue.Queue()
        self._on_receive = None
        self._on_response = None
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name="hvd-ctrl-recv", daemon=True)
        self._recv_thread.start()
        self._send_lock = threading.Lock()

    def set_receive_callback(self, fn):
        """Called (from the recv thread) whenever a frame is queued —
        the runtime wires its wake event here so response pickup is
        event-driven instead of a poll."""
        self._on_receive = fn

    def set_replay_observer(self, observer):
        """Steady-state replay hook (common/replay.py): the recv thread
        reports response/eviction/param frames so the tracker can
        detect converged cycles and exit replay on invalidation.
        Observation happens BEFORE delivery, so by the time a blocked
        submitter wakes the tracker has already recorded its response."""
        self._replay_observer = observer

    def set_response_callback(self, fn):
        """Direct dispatch: the recv thread executes each response by
        calling ``fn(response)`` the moment its frame is decoded,
        instead of queuing for the background thread.  On a 1-core
        host every thread handoff is a context switch, so cutting the
        recv->queue->background hop removes a fixed ~0.1-0.2 ms from
        per-op latency (the reference instead pays its fixed cycle
        sleep, operations.cc:587).  Ordering is inherited from the
        coordinator's broadcast order because the recv loop is the
        single, sequential consumer of the socket.  PA markers apply
        in-stream between executed batches for free."""
        self._on_response = fn

    def _make_server(self, state, port, param_manager):
        """Prefer the native C++ coordinator (horovod_tpu/native); fall
        back to the Python CoordinatorServer.  The Python server is
        also used when a timeline is active (negotiation spans are
        recorded coordinator-side), when cross-rank metrics
        aggregation is requested (MQ/MR frames), and while the
        autotuner runs (the
        parameter manager scores real per-round byte counts in-line and
        announces categorical knobs via PA frames — higher-fidelity
        than the native counter-polling path it replaces)."""
        allow_ephemeral = self._rendezvous_client() is not None
        stall_warn = 0.0 if state.knobs.stall_check_disable else \
            state.knobs.stall_warning_time_s
        # When the user EXPLICITLY set HOROVOD_TPU_NATIVE to a truthy
        # value, a missing/broken native build is an error, not a
        # silent fallback — otherwise native-path tests pass vacuously
        # against the Python coordinator.
        strict_native = os.environ.get(
            "HOROVOD_TPU_NATIVE", "").strip().lower() in ("1", "true",
                                                          "on", "yes")
        if strict_native and param_manager is not None:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_AUTOTUNE=1: the autotuner requires the Python "
                "coordinator (in-line scoring + PA parameter frames). "
                "Unset one of the two.")
        metrics_interval = state.knobs.metrics_agg_interval_s
        if strict_native and metrics_interval > 0:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_METRICS_AGG_SECONDS>0: cross-rank metrics "
                "aggregation requires the Python coordinator (MQ/MR "
                "frames).  Unset one of the two.")
        # Armed failpoints pin the Python coordinator: the native C++
        # coordinator carries no injection sites, and a fault schedule
        # that silently skipped its coord.*/worker.* rules would report
        # a vacuous pass.  Strict-native + failpoints is a config error.
        if strict_native and _fp.ENABLED:
            raise RuntimeError(
                "HOROVOD_TPU_NATIVE=1 is incompatible with "
                "HOROVOD_FAILPOINTS: fault injection requires the "
                "Python coordinator.  Unset one of the two.")
        if state.timeline is None and param_manager is None and \
                metrics_interval <= 0 and not _fp.ENABLED:
            try:
                from ..native import NativeCoordinatorServer, available
                if strict_native and not available():
                    raise RuntimeError(
                        "HOROVOD_TPU_NATIVE is set but the native "
                        "coordinator could not be built/loaded")
                if available():
                    return NativeCoordinatorServer(
                        self.size, port=port,
                        fusion_threshold=(
                            state.knobs.fusion_threshold_bytes),
                        elastic=state.knobs.elastic,
                        allow_ephemeral_fallback=allow_ephemeral,
                        cache_capacity=state.knobs.cache_capacity,
                        stall_warning_time_s=stall_warn,
                        stall_shutdown_time_s=(
                            state.knobs.stall_shutdown_time_s))
            except OSError:
                raise   # bind failure: same semantics as Python server
            except Exception:
                if strict_native:
                    raise
                logger.warning("native coordinator unavailable; using "
                               "the Python coordinator", exc_info=True)
        return CoordinatorServer(
            self.size, port=port,
            fusion_threshold=state.knobs.fusion_threshold_bytes,
            timeline=state.timeline,
            elastic=state.knobs.elastic,
            allow_ephemeral_fallback=allow_ephemeral,
            param_manager=param_manager,
            cache_capacity=state.knobs.cache_capacity,
            stall_warning_time_s=stall_warn,
            stall_shutdown_time_s=state.knobs.stall_shutdown_time_s,
            metrics_interval_s=metrics_interval)

    @staticmethod
    def _rendezvous_client():
        from ..runner.http_server import RendezvousClient
        addr = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
        port = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT")
        if not addr or not port:
            return None
        return RendezvousClient(addr, int(port))

    def _ctrl_scope(self) -> str:
        # Per-epoch scope so elastic re-inits don't read a stale addr.
        epoch = os.environ.get("HOROVOD_CONTROLLER_ADDR", "")
        return f"controller.{epoch}"

    def _publish_actual_addr(self, env_addr, actual_port):
        """Rank 0: publish the actually-bound controller address to the
        rendezvous KV store (guards against the launcher-chosen port
        being taken by the time rank 0 binds it)."""
        client = self._rendezvous_client()
        if client is None:
            return
        host = env_addr.rsplit(":", 1)[0] if env_addr else "127.0.0.1"
        try:
            client.put(self._ctrl_scope(), "addr",
                       f"{host}:{actual_port}".encode())
        except OSError:
            logger.warning("could not publish controller addr to "
                           "rendezvous", exc_info=True)

    def _resolve_addr(self, env_addr):
        """Workers: prefer the rendezvous-published address; fall back
        to the env contract (used when no rendezvous server exists)."""
        client = self._rendezvous_client()
        if client is not None:
            timeout_s = float(os.environ.get("HOROVOD_START_TIMEOUT",
                                             120))
            try:
                raw = client.wait_get(self._ctrl_scope(), "addr",
                                      timeout=timeout_s)
                return raw.decode()
            except (OSError, TimeoutError):
                logger.warning("rendezvous controller-addr lookup "
                               "failed; using env value")
        return env_addr

    def _connect(self) -> socket.socket:
        # HOROVOD_START_TIMEOUT bounds the wait for the coordinator to
        # come up (launcher --start-timeout; reference launch.py
        # start_timeout contract).
        timeout_s = float(os.environ.get("HOROVOD_START_TIMEOUT", 120))
        deadline = time.monotonic() + timeout_s
        last_err = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(self._addr, timeout=5.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(None)
                _send_frame(s, _MAGIC_REQ, struct.pack("<i", self.rank))
                return s
            except OSError as e:
                last_err = e
                time.sleep(0.2)
        raise ConnectionError(
            f"could not reach coordinator at {self._addr}: {last_err}")

    def set_broken_callback(self, fn):
        """Called once (from the recv thread) when the control-plane
        connection dies mid-incarnation, so the runtime can fail fast
        instead of waiting for the next submission to notice."""
        self._on_broken = fn

    def _set_broken(self, err):
        self._broken_err = err
        if self._replay_observer is not None:
            self._replay_observer.on_broken()
        cb = getattr(self, "_on_broken", None)
        if cb is not None:
            try:
                cb(err)
            except Exception:
                logger.warning("broken-callback failed", exc_info=True)

    def _recv_loop(self):
        while True:
            try:
                frame = _recv_frame(self._sock)
            except OSError:
                frame = None
            if frame is None:
                if not self._closing:
                    from .exceptions import HorovodInternalError
                    self._set_broken(HorovodInternalError(
                        "connection to the coordinator was lost "
                        "(membership changed or rank 0 exited)"))
                return
            magic, payload = frame
            # Failpoint site: downlink frame arrival on a worker.
            # drop() loses one response/cache frame for THIS rank only
            # — it falls out of lockstep with its peers, the shape of
            # desync the coordinator's attribution must survive.
            # error() models a corrupt/dead downlink and must route
            # through the broken-connection path: letting it kill this
            # recv thread bare would leave blocked synchronize()
            # callers hanging with no one to fail them.
            if _fp.ENABLED:
                try:
                    if _fp.maybe_fail("worker.frame_recv",
                                      rank=self.rank) == "drop":
                        continue
                except _fp.FailpointError as e:
                    from .exceptions import HorovodInternalError
                    self._set_broken(HorovodInternalError(str(e)))
                    return
            self.stats["bytes_recv"] += len(payload) + 6
            _BYTES_RECV.inc(len(payload) + 6)
            _FRAMES_RECV.inc(1, kind=magic.decode("ascii", "replace"))
            if magic == _MAGIC_METRICS_REQ:
                self._spawn_metrics_reply()
                continue
            if magic == _MAGIC_CACHE:
                self.stats["cb_frames"] += 1
                batches = unpack_bit_batches(payload)
                responses = self._reconstruct_cached(batches)
                if responses is None:
                    return  # desync; _broken_err set
                if self._replay_observer is not None:
                    self._replay_observer.on_responses(
                        "cb", list(zip(responses, batches)))
                self._deliver(responses)
                continue
            if magic == _MAGIC_EVICT:
                self.stats["ev_frames"] += 1
                bits = unpack_bits(payload)
                self.cache.evict_bits(bits)
                if self._replay_observer is not None:
                    self._replay_observer.on_evictions(bits)
                continue
            if magic == _MAGIC_ABORT:
                from .exceptions import HorovodInternalError
                self._set_broken(HorovodInternalError(
                    payload.decode(errors="replace")))
                return
            if magic == _MAGIC_PARAMS:
                self.stats["pa_frames"] += 1
                params = json.loads(payload.decode())
                if self._replay_observer is not None:
                    self._replay_observer.on_params()
                if self._on_response is not None:
                    # Direct dispatch executes batches in-stream, so
                    # by the time the PA frame is decoded every batch
                    # received before it has already run — apply
                    # immediately; every worker flips knobs at the
                    # same logical point.
                    self._apply_params(params)
                else:
                    # Queued as an in-stream marker: the runtime
                    # applies it exactly between the batches it
                    # arrived between (hierarchical on/off changes the
                    # compiled collective program — a half-flipped
                    # world would hang).
                    self._recv_buf.put(("PA", params))
                    if self._on_receive is not None:
                        self._on_receive()
                continue
            self.stats["rs_frames"] += 1
            responses, _ = unpack_response_list(payload)
            self._seed_cache(responses)
            if self._replay_observer is not None:
                self._replay_observer.on_responses(
                    "rs", [(r, ()) for r in responses])
            self._deliver(responses)

    def _send_frame_counted_locked(self, magic: bytes, payload: bytes,
                                   stat_key: str, kind: str):
        """One uplink frame + its stats-dict and registry accounting in
        lockstep (caller holds self._send_lock) — the single place the
        frame-header byte math lives on the send side."""
        # Failpoint site: worker uplink.  drop() swallows the RQ/CH
        # frame before the socket — the coordinator never learns this
        # rank is ready, so the tensor must surface through rank-0
        # stall attribution, not a hang.
        if _fp.ENABLED and \
                _fp.maybe_fail("worker.frame_send",
                               rank=self.rank) == "drop":
            return
        _send_frame(self._sock, magic, payload)
        self.stats[stat_key] = self.stats.get(stat_key, 0) + 1
        self.stats["bytes_sent"] += len(payload) + 6
        _FRAMES_SENT.inc(1, kind=kind)
        _BYTES_SENT.inc(len(payload) + 6)

    def _spawn_metrics_reply(self):
        """MR replies ride their own short-lived thread: the recv
        thread must NEVER block on _send_lock — a recv thread waiting
        on a send while both TCP buffers are full closes a distributed
        deadlock cycle with the coordinator's broadcast lock (coord
        holds its lock writing to us, our submit thread holds
        _send_lock writing to the coord, the coord's rank loop waits
        on its lock, we'd wait here).  At most one reply in flight; a
        poll arriving while the previous reply is still blocked is
        dropped — snapshots are absolute, the next poll re-covers it.
        The flag is advisory (set here, cleared by the reply thread):
        the worst race outcome is one dropped poll."""
        if self._mr_sending:
            return
        self._mr_sending = True

        def run():
            try:
                self._send_metrics_snapshot()
            finally:
                self._mr_sending = False

        threading.Thread(target=run, name="hvd-metrics-reply",
                         daemon=True).start()

    def _send_metrics_snapshot(self):
        """MQ poll answer: ship this process's registry snapshot to
        the coordinator."""
        try:
            payload = json.dumps(metrics.snapshot()).encode()
        except (TypeError, ValueError):
            logger.warning("metrics snapshot not serializable",
                           exc_info=True)
            return
        try:
            with self._send_lock:
                self._send_frame_counted_locked(
                    _MAGIC_METRICS_REP, payload, "mr_frames", "MR")
        except OSError:
            pass  # connection teardown races the poll; never fatal

    def _deliver(self, responses: List[Response]):
        if self._on_response is not None:
            for resp in responses:
                self._on_response(resp)
            return
        self._recv_buf.put(responses)
        if self._on_receive is not None:
            self._on_receive()

    def _seed_cache(self, responses: List[Response]):
        """Store per-tensor slices of newly negotiated responses under
        the coordinator-assigned bits.  Entries for tensors this rank
        never submitted (process-set non-members, joined ranks) carry no
        signature: they resolve CB bits but never produce hits."""
        if not self.cache.enabled:
            return
        for resp in responses:
            if resp.response_type not in CACHEABLE or not resp.cache_bits:
                self._seed_log.append(
                    ("skip", resp.tensor_names, resp.process_set_id,
                     list(resp.cache_bits or ())))
                continue
            parts = split_response(resp, self.size)
            for i, name in enumerate(resp.tensor_names):
                bit = resp.cache_bits[i] if i < len(resp.cache_bits) else -1
                if bit < 0:
                    self._seed_log.append(("nobit", name,
                                           resp.process_set_id))
                    continue
                key = (resp.process_set_id, name)
                self._seed_log.append(("seed", bit, key))
                self.cache.insert(key, bit, parts[i],
                                  self._sent_sigs.get(key))

    def _reconstruct_cached(self, batches: List[List[int]]
                            ) -> Optional[List[Response]]:
        """CB frame: rebuild the fused responses from the local cache.
        By protocol a CB batch only fires when every member rank
        contributed via bit, which implies every rank (member or not)
        still holds the entries — an unknown bit is a hard desync."""
        responses = []
        for batch in batches:
            parts = [self.cache.response_for_bit(b) for b in batch]
            if any(p is None for p in parts):
                from .exceptions import HorovodInternalError
                missing = [b for b, p in zip(batch, parts) if p is None]
                self._set_broken(HorovodInternalError(
                    "response-cache desync: coordinator referenced "
                    "cache bit(s) %s this rank does not hold (batch "
                    "%s; held: %s; frames: %s; seeds: %s)" % (
                        missing, batch, self.cache.debug_bits(),
                        {k: v for k, v in self.stats.items()
                         if k.endswith("_frames")},
                        list(self._seed_log)[-12:])))
                return None
            responses.append(merge_responses(parts))
        return responses

    def try_inline_cache_hit(self, request) -> bool:
        """Submitting-thread fast path (reference cycle analog:
        operations.cc:587-645 cache-hit short circuit): on a
        response-cache hit, the caller thread sends the CH frame
        itself and returns — the background thread never wakes for
        this op, and with direct dispatch the response executes on the
        recv thread, so a steady-state eager op costs ONE context
        switch (recv -> waiting caller) instead of four.  Returns
        False on a miss (caller falls back to the negotiation queue).
        """
        if self._broken_err is not None:
            raise self._broken_err
        if not self.cache.enabled:
            return False
        # count_miss=False: a missed request falls back to the cycle,
        # whose own lookup counts the same logical miss.
        bit = self.cache.lookup_bit(request, count_miss=False)
        if bit is None:
            _INLINE.inc(1, result="miss")
            return False
        _INLINE.inc(1, result="hit")
        try:
            with self._send_lock:
                self._send_frame_counted_locked(
                    _MAGIC_HITS, pack_bits([bit]), "ch_frames", "CH")
        except OSError as e:
            from .exceptions import HorovodInternalError
            raise HorovodInternalError(
                f"could not reach the coordinator: {e}") from e
        return True

    def compute_response_list(self, pending, entry_sizes, threshold_bytes):
        if self._broken_err is not None:
            raise self._broken_err
        if pending:
            hit_bits: List[int] = []
            full: List[Request] = []
            # Group atomicity: a grouped submission travels in ONE
            # frame per rank (runtime.submit_group + pop_pending), so
            # demoting the WHOLE group to full requests whenever any
            # member misses the cache keeps all members' completion
            # counts in lockstep on the coordinator — members can
            # never finish in different rounds (one in a CB batch,
            # another in a later RS frame).
            lookups = [self.cache.lookup_bit(req)
                       if self.cache.enabled else None
                       for req in pending]
            demoted_gids = {req.group_id
                            for req, bit in zip(pending, lookups)
                            if bit is None and req.group_id >= 0}
            for req, bit in zip(pending, lookups):
                if bit is not None and (req.group_id < 0 or
                                        req.group_id not in demoted_gids):
                    hit_bits.append(bit)
                else:
                    full.append(req)
                    self._sent_sigs[(req.process_set_id,
                                     req.tensor_name)] = \
                        request_signature(req)
            try:
                with self._send_lock:
                    if hit_bits:
                        _UPLINK_BATCH.observe(len(hit_bits), kind="CH")
                        self._send_frame_counted_locked(
                            _MAGIC_HITS, pack_bits(hit_bits),
                            "ch_frames", "CH")
                    if full:
                        _UPLINK_BATCH.observe(len(full), kind="RQ")
                        self._send_frame_counted_locked(
                            _MAGIC_REQ, pack_request_list(full),
                            "rq_frames", "RQ")
            except OSError as e:
                from .exceptions import HorovodInternalError
                raise HorovodInternalError(
                    f"could not reach the coordinator: {e}") from e
        if self._pending_params is not None:
            # Everything returned before the PA marker has executed by
            # now (the runtime performs responses before calling back).
            self._apply_params(self._pending_params)
            self._pending_params = None
        responses: List[Response] = []
        try:
            # Non-blocking drain: the recv thread wakes the runtime's
            # cycle event on arrival (set_receive_callback), so there
            # is no poll-interval latency floor here.
            item = self._recv_buf.get_nowait()
            while True:
                if isinstance(item, tuple) and item[0] == "PA":
                    if responses:
                        # Batches before the marker must execute first.
                        self._pending_params = item[1]
                        break
                    self._apply_params(item[1])
                else:
                    responses.extend(item)
                item = self._recv_buf.get_nowait()
        except queue.Empty:
            pass
        return responses, []

    def _apply_params(self, params: dict):
        """Adopt autotuned parameters announced by the coordinator
        (reference: Controller::SynchronizeParameters)."""
        if "hierarchical" in params:
            self.state.knobs.hierarchical_allreduce = \
                bool(params["hierarchical"])

    def shutdown(self):
        self._closing = True
        try:
            with self._send_lock:
                _send_frame(self._sock, _MAGIC_REQ,
                            pack_request_list([], shutdown=True))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self.server is not None:
            self._drain_server()
            self.server.stop()

    # Grace window: if the set of ever-connected ranks is stagnant and
    # all of them departed, remaining ranks crashed before connecting —
    # no point waiting out the full timeout.
    _DRAIN_STAGNATION_S = 5.0

    def _drain_server(self):
        """Keep serving until every rank departed, so ranks still
        initializing (or draining) can reach the coordinator (the
        reference's background thread likewise serves until all ranks
        shut down, operations.cc:539-585).  Elastic resets use a short
        cap: peers fail over via the broken-membership path anyway."""
        timeout = 5.0 if self.state.knobs.elastic else \
            float(os.environ.get("HOROVOD_START_TIMEOUT", 120))
        deadline = time.monotonic() + timeout
        prev_seen = -1
        stagnant_since = time.monotonic()
        while time.monotonic() < deadline:
            seen, departed = self.server.departure_counts()
            if departed >= self.size:
                return
            now = time.monotonic()
            if seen != prev_seen:
                prev_seen = seen
                stagnant_since = now
            elif departed >= seen and \
                    now - stagnant_since > self._DRAIN_STAGNATION_S:
                logger.warning(
                    "stopping coordinator: %d/%d ranks never "
                    "connected", self.size - seen, self.size)
                return
            time.sleep(0.1)
        logger.warning("stopping coordinator with ranks still attached "
                       "(waited %.0fs)", timeout)
