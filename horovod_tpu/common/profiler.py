"""Continuous low-Hz sampling profiler: *why* is this rank slow.

The observatory (common/straggler.py) names WHICH rank is slow and the
flight recorder (common/flight_recorder.py) reconstructs WHAT happened
after a failure; this module answers the remaining live question — what
the slow rank's threads are actually DOING — without a restart, a
debugger, or per-call instrumentation.  Same lineage as the rest of the
plane (Dapper / the NCCL flight recorder, PAPERS.md): always-on cheap
attribution, analysis out-of-band.

Mechanism: a daemon thread walks ``sys._current_frames()`` at
``HOROVOD_PROFILE_HZ`` (default 10 Hz — a wall-clock sampling profiler,
py-spy-shaped, not a tracing one; overhead is O(threads × depth) dict
walks per tick, independent of op rate).  Each sample is collapsed into
a ``thread;module:func;...;module:func`` stack string and attributed to
a *subsystem lane* by the modules on the stack:

* ``submit``      — user/framework submission path (runtime.submit,
  tensor queue, ops dispatch, failpoint delays injected there);
* ``controller``  — negotiation / frame plane (controller_net, relay);
* ``ring``        — data-plane backends (horovod_tpu/ops);
* ``replay``      — steady-state replay matching;
* ``checkpoint``  — shard write / restore paths;
* ``other``       — anything else (user code, jax internals).

Two derived shares ride along, both *estimates* (a pure-Python sampler
cannot see C frames): ``blocking_share`` — samples whose leaf is a
known blocking/wait call (recv/select/wait/sleep/fsync...), and
``gil_wait_share`` — the mean of (runnable−1)/runnable over samples,
i.e. the fraction of runnable-thread time that must be spent waiting
for the GIL given how many threads were simultaneously runnable.

Transport: each rank folds its top-K hot frames (framework waits
excluded — a recv loop parked on a socket is where threads *park*, not
where time is *lost*) into rank-labeled gauge children
(``hvd_prof_hot_share{rank,k,lane,frame}``) on the cold MR-reply path,
so the digest rides the EXISTING metrics frames and survives relay
MR→MA pre-aggregation exactly like the straggler phase summaries (each
rank only ever writes its own label).  Rank 0 can therefore always say
"rank 3 is slow in shard_io:fsync" from digests alone.  The full
collapsed-stack profile is served per rank at job-secret
``GET /profile`` (tools/flame.py merges and renders them).

Triggered capture: a straggler flag, a stall warning, or an SLO burn
crossing calls :func:`trigger_capture` — the last window's dominant
frames are attached to one flight-recorder PROFILE event and kept as
``last_capture`` in the /profile payload, so the postmortem carries the
live profile at the moment the symptom fired (throttled; captures are
cheap but a flapping trigger must not spam the ring).

Design constraints (the trigger sites live on warning/refresh paths;
the sampler itself owns its cost):

  * one module-attribute check when disabled — every feeder site is
    written ``if profiler.ENABLED: profiler.trigger_capture(...)``,
    the failpoints/flight-recorder/straggler precedent, pinned by
    tests/test_profiler.py and policed by the hvdlint hot-path gate;
  * bounded memory — collapsed-stack aggregation is capped
    (_MAX_STACKS; overflow folds into a ``(truncated)`` bucket) and
    the trigger window is a fixed-size deque;
  * the sampler never takes project locks — ``sys._current_frames``
    is a snapshot, frame walks touch only interpreter state.
"""

import logging
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import env as _env
from . import flight_recorder as _fr
from . import metrics

logger = logging.getLogger("horovod_tpu.profiler")

# THE disabled-path gate: every feeder site checks this one module
# attribute before anything else.  configure()/reset() are the only
# writers (the failpoints/flight_recorder/straggler precedent).
ENABLED = False

_MAX_DEPTH = 48          # frames kept per stack (leaf-most win)
_MAX_STACKS = 512        # distinct collapsed stacks retained per lane
_CAPTURE_THROTTLE_S = 1.0
_WINDOW_SAMPLES = 4096   # trigger-capture window (ring of samples)

_HOT = metrics.gauge(
    "hvd_prof_hot_share",
    "Per-rank top-K hot frames from the sampling profiler: share of "
    "active samples attributed to {frame} in {lane}, published into "
    "MR metrics frames (k orders the digest)")
_GIL_WAIT = metrics.gauge(
    "hvd_prof_gil_wait_share",
    "Estimated share of runnable-thread time spent waiting on the GIL "
    "(mean of (runnable-1)/runnable per sample), by rank")
_BLOCKING = metrics.gauge(
    "hvd_prof_blocking_share",
    "Share of samples whose leaf frame is a known blocking/wait call "
    "(recv/select/wait/sleep/fsync/...), by rank")
_SAMPLES = metrics.counter(
    "hvd_prof_samples_total",
    "Stack samples taken by the profiler thread, by rank")
_CAPTURES = metrics.counter(
    "hvd_prof_captures_total",
    "Triggered profile captures, by trigger reason "
    "(straggler / stall / slo_burn / manual)")

# Leaf function names that indicate a blocking syscall / wait under
# the leaf Python frame (the C callee is invisible to the sampler).
_BLOCKING_LEAF = frozenset((
    "wait", "acquire", "sleep", "select", "poll", "recv", "recv_into",
    "recvfrom", "accept", "read", "readinto", "write", "flush",
    "fsync", "join", "get", "send", "sendall", "connect",
))
# stdlib wait machinery: a leaf here means the thread is parked in
# framework plumbing (Event.wait, queue.get, selector loops) — counted
# into blocking_share but excluded from the hot-frame digest.
_IDLE_MODULES = frozenset((
    "threading", "selectors", "queue", "socketserver", "ssl",
))
# Project-side park points: receive/poll loops that are *supposed* to
# sit in a blocking call all day.  Keeping them out of the digest is
# what lets the digest answer "where is time LOST" instead of "where
# do threads WAIT" — a curated list, not a heuristic, because the
# profiler ships with the runtime it profiles.
_PARK_FUNCS = frozenset((
    "_recv_exact", "recv_exact", "_recv_exact_bounded", "recv_frame",
    "_recv_frame_bounded", "_recv_loop", "_parent_recv_loop",
    "_uplink_loop", "_accept_loop", "_mux_loop", "serve_forever",
    "_metrics_loop", "_straggler_loop", "_stall_loop", "_hb_loop",
    "_liveness_loop", "_sampler_loop", "_eval_loop", "_loop",
    "handle_request", "poll_once",
))

# Lane attribution by module basename (leaf-most project frame wins).
_LANE_BY_MODULE = {
    "runtime": "submit",
    "tensor_queue": "submit",
    "failpoints": "submit",
    "controller": "controller",
    "controller_net": "controller",
    "relay": "controller",
    "message": "controller",
    "replay": "replay",
}
_LANES = ("submit", "controller", "ring", "replay", "checkpoint",
          "other")


def _classify(filenames: List[str], funcs: List[str]) -> str:
    """Lane of a stack (leaf-most attributable frame wins)."""
    for fname, func in zip(filenames, funcs):
        if "horovod_tpu" not in fname:
            continue
        if "/checkpoint/" in fname:
            return "checkpoint"
        if "/ops/" in fname:
            return "ring"
        mod = fname.rsplit("/", 1)[-1][:-3]
        lane = _LANE_BY_MODULE.get(mod)
        if lane is not None:
            return lane
    return "other"


def _frame_name(filename: str, func: str) -> str:
    """``module:func`` — short, stable, label-safe (no ',', '=', '"'
    — the metrics label sanitizer would mangle them)."""
    base = filename.rsplit("/", 1)[-1]
    if base.endswith(".py"):
        base = base[:-3]
    return "%s:%s" % (base, func)


class SamplingProfiler:
    """The per-process sampler (one per interpreter — threads are a
    process-wide resource, unlike the per-runtime PhaseCollector)."""

    def __init__(self, hz: Optional[float] = None,
                 topk: Optional[int] = None):
        self.hz = float(hz) if hz is not None else _env.profile_hz()
        self.topk = int(topk) if topk is not None \
            else _env.profile_topk()
        self.rank: Optional[int] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.monotonic()
        # (lane, collapsed_stack) -> sample count, active only.
        self._counts: Dict[tuple, int] = {}
        self._lane_totals: Dict[str, int] = {}
        self._samples = 0          # sampling ticks
        self._thread_samples = 0   # per-thread stack samples
        self._blocking = 0
        self._gil_accum = 0.0
        # Recent active samples for triggered capture: (t, lane, stack).
        self._window = deque(maxlen=_WINDOW_SAMPLES)
        self._last_capture: Optional[dict] = None
        self._last_capture_t = 0.0

    # -- lifecycle -----------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._sampler_loop, name="hvd-profiler",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling ------------------------------------------------------
    def _sampler_loop(self):
        interval = 1.0 / max(0.1, self.hz)
        me = threading.get_ident()
        while not self._stop.wait(interval):
            try:
                self._sample_once(me)
            except Exception:
                # A sampler crash must never take down training; the
                # profile just stops advancing.
                logger.warning("profiler sample failed", exc_info=True)

    def _sample_once(self, self_ident: int):
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        now = time.monotonic()
        runnable = 0
        batch = []  # (lane, stack, active, blocking)
        for ident, frame in frames.items():
            if ident == self_ident:
                continue
            files: List[str] = []
            funcs: List[str] = []
            f = frame
            depth = 0
            while f is not None and depth < _MAX_DEPTH:
                files.append(f.f_code.co_filename.replace("\\", "/"))
                funcs.append(f.f_code.co_name)
                f = f.f_back
                depth += 1
            if not funcs:
                continue
            leaf_file, leaf_func = files[0], funcs[0]
            leaf_mod = leaf_file.rsplit("/", 1)[-1][:-3] \
                if leaf_file.endswith(".py") \
                else leaf_file.rsplit("/", 1)[-1]
            blocking = (leaf_func in _BLOCKING_LEAF or
                        leaf_mod in _IDLE_MODULES)
            parked = (leaf_mod in _IDLE_MODULES or
                      any(fn in _PARK_FUNCS for fn in funcs[:3]))
            active = not parked
            if active and not blocking:
                runnable += 1
            lane = _classify(files, funcs)
            tname = names.get(ident, "t%d" % ident)
            # Root→leaf collapsed stack, thread name as the root frame
            # (flamegraph convention; also the only per-"rank" signal
            # the in-process chaos harness has).
            stack = ";".join(
                [_frame_name(tname, "thread")] +
                [_frame_name(fl, fn)
                 for fl, fn in zip(reversed(files), reversed(funcs))])
            batch.append((lane, stack, active, blocking))
        with self._lock:
            self._samples += 1
            for lane, stack, active, blocking in batch:
                self._thread_samples += 1
                if blocking:
                    self._blocking += 1
                if not active:
                    continue
                self._lane_totals[lane] = \
                    self._lane_totals.get(lane, 0) + 1
                key = (lane, stack)
                if key in self._counts or \
                        len(self._counts) < _MAX_STACKS:
                    self._counts[key] = self._counts.get(key, 0) + 1
                else:
                    over = (lane, "(truncated)")
                    self._counts[over] = self._counts.get(over, 0) + 1
                self._window.append((now, lane, stack))
            if runnable > 1:
                self._gil_accum += (runnable - 1) / float(runnable)
        if self.rank is not None:
            _SAMPLES.inc(1, rank=self.rank)
        else:
            _SAMPLES.inc(1, rank="unset")

    # -- reading -------------------------------------------------------
    @staticmethod
    def _leaf(stack: str) -> str:
        return stack.rsplit(";", 1)[-1]

    def top_frames(self, k: Optional[int] = None) -> List[dict]:
        """Top-k hot frames by active-sample share: the leaf frame of
        the hottest collapsed stacks, folded per (lane, leaf)."""
        k = k if k is not None else self.topk
        with self._lock:
            counts = dict(self._counts)
            total = sum(self._lane_totals.values())
        if not total:
            return []
        by_leaf: Dict[tuple, int] = {}
        for (lane, stack), n in counts.items():
            key = (lane, self._leaf(stack))
            by_leaf[key] = by_leaf.get(key, 0) + n
        ranked = sorted(by_leaf.items(), key=lambda kv: -kv[1])[:k]
        return [{"lane": lane, "frame": frame,
                 "share": round(n / total, 4)}
                for (lane, frame), n in ranked]

    def collapsed(self) -> Dict[str, int]:
        """``stack -> count`` (flame-ready: one ``stack count`` line
        each; the stack already carries the lane as metadata via its
        thread-name root)."""
        with self._lock:
            return {stack: n
                    for (_lane, stack), n in self._counts.items()}

    def profile_dict(self) -> dict:
        """The GET /profile payload (JSON-ready)."""
        with self._lock:
            samples = self._samples
            tsamples = self._thread_samples
            blocking = self._blocking
            gil = self._gil_accum
            lanes = dict(self._lane_totals)
            last = self._last_capture
        return {
            "enabled": True,
            "rank": self.rank,
            "hz": self.hz,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "samples": samples,
            "thread_samples": tsamples,
            "blocking_share": round(blocking / tsamples, 4)
            if tsamples else 0.0,
            "gil_wait_share": round(gil / samples, 4)
            if samples else 0.0,
            "lanes": lanes,
            "top": self.top_frames(),
            "collapsed": self.collapsed(),
            "last_capture": last,
        }

    # -- triggered capture --------------------------------------------
    def capture(self, reason: str, detail: str = "") -> Optional[dict]:
        """Snapshot the dominant frames of the last window; throttled.
        Returns the capture dict (None when throttled or empty)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_capture_t < _CAPTURE_THROTTLE_S:
                return None
            self._last_capture_t = now
            window = list(self._window)
        counts: Dict[tuple, int] = {}
        for _t, lane, stack in window:
            key = (lane, self._leaf(stack))
            counts[key] = counts.get(key, 0) + 1
        total = len(window)
        top = sorted(counts.items(), key=lambda kv: -kv[1])[:self.topk]
        cap = {
            "reason": reason,
            "detail": detail,
            "wall": time.time(),
            "window_samples": total,
            "top": [{"lane": lane, "frame": frame,
                     "share": round(n / total, 4) if total else 0.0}
                    for (lane, frame), n in top],
        }
        with self._lock:
            self._last_capture = cap
        _CAPTURES.inc(1, reason=reason)
        if _fr.ENABLED:
            _fr.record(_fr.PROFILE, rank=self.rank, reason=reason,
                       detail=detail[:120],
                       frames=" ".join(
                           "%s@%s" % (e["frame"], e["share"])
                           for e in cap["top"][:3]))
        return cap

    # -- MR digest -----------------------------------------------------
    def publish_digest(self, rank: int):
        """Fold the top-K digest + derived shares into rank-labeled
        gauges so the NEXT MR reply carries them (cold, MR cadence).
        Each rank only ever writes its OWN label — the relay MA
        pre-aggregation survival contract (common/straggler.py)."""
        self.rank = rank
        # Retire this rank's previous digest first: the hot set drifts
        # between publishes, and a stale (k, frame) child would
        # otherwise shadow the fresh one in every later extraction.
        _HOT.drop(rank=rank)
        for k, entry in enumerate(self.top_frames()):
            _HOT.set(entry["share"], rank=rank, k=k,
                     lane=entry["lane"], frame=entry["frame"])
        with self._lock:
            samples = self._samples
            tsamples = self._thread_samples
            blocking = self._blocking
            gil = self._gil_accum
        if tsamples:
            _BLOCKING.set(round(blocking / tsamples, 4), rank=rank)
        if samples:
            _GIL_WAIT.set(round(gil / samples, 4), rank=rank)


# ---------------------------------------------------------------------------
# module-level lifecycle + the digest extraction inverse
# ---------------------------------------------------------------------------

_PROFILER: Optional[SamplingProfiler] = None


def configure(enabled: bool = True, hz: Optional[float] = None,
              topk: Optional[int] = None):
    """(Re)arm the profiler: starts (or stops) the sampling thread.
    Hz/top-K are read freshly from the env unless pinned (drills sweep
    them per phase)."""
    global ENABLED, _PROFILER
    if not enabled:
        reset()
        return
    if _PROFILER is not None:
        _PROFILER.stop()
    _PROFILER = SamplingProfiler(hz=hz, topk=topk)
    _PROFILER.start()
    ENABLED = True
    logger.debug("profiler armed (%.1f Hz, top-%d)",
                 _PROFILER.hz, _PROFILER.topk)


def reset():
    """Disable the profiler and stop its thread (tests/drills)."""
    global ENABLED, _PROFILER
    ENABLED = False
    if _PROFILER is not None:
        _PROFILER.stop()
        _PROFILER = None


def instance() -> Optional[SamplingProfiler]:
    return _PROFILER


def set_rank(rank: int):
    """Stamp the owning rank (mirrors flight_recorder.set_rank)."""
    p = _PROFILER
    if p is not None:
        p.rank = rank


def publish_digest(rank: int):
    """Feeder site for the MR-reply path; gate on ENABLED there."""
    p = _PROFILER
    if p is not None:
        p.publish_digest(rank)


def trigger_capture(reason: str, detail: str = ""):
    """Feeder site for straggler/stall/SLO triggers; gate on ENABLED
    at the call site (one attribute check when disabled)."""
    p = _PROFILER
    if p is not None:
        p.capture(reason, detail)


def profile_dict() -> dict:
    """GET /profile payload; self-describing when disarmed."""
    p = _PROFILER
    if p is None:
        return {"enabled": False}
    return p.profile_dict()


def collapsed_text(profile: dict) -> str:
    """Render a /profile payload's collapsed stacks as flamegraph
    input lines (``stack count``, brendangregg collapsed format)."""
    lines = ["%s %d" % (stack, n)
             for stack, n in sorted(
                 (profile.get("collapsed") or {}).items())]
    return "\n".join(lines) + ("\n" if lines else "")


def digest_from_snapshot(snap: dict) -> Dict[int, List[dict]]:
    """Extract ``{rank: [{k, lane, frame, share}, ...]}`` (k-ordered)
    from a metrics snapshot (an MR reply, a relay MA aggregate, or the
    merged cluster view) — the inverse of publish_digest()'s
    rank-labeled gauges, the phases_from_snapshot shape."""
    out: Dict[int, List[dict]] = {}
    gauges = snap.get("gauges", {}) if isinstance(snap, dict) else {}
    children = gauges.get("hvd_prof_hot_share")
    if not isinstance(children, dict):
        return out
    for key, value in children.items():
        labels = dict(item.split("=", 1)
                      for item in key.split(",") if "=" in item)
        try:
            rank = int(labels["rank"])
            entry = {"k": int(labels["k"]), "lane": labels["lane"],
                     "frame": labels["frame"],
                     "share": float(value)}
        except (KeyError, ValueError, TypeError):
            continue
        out.setdefault(rank, []).append(entry)
    for rank in out:
        out[rank].sort(key=lambda e: e["k"])
    return out


def describe_digest(entries: Optional[List[dict]]) -> str:
    """One human root-cause clause from a rank's digest: the dominant
    frame + its lane/share — the text stall warnings and drill
    verdicts attach."""
    if not entries:
        return ""
    top = entries[0]
    return "%s (%s lane, %d%% of samples)" % (
        top.get("frame", "?"), top.get("lane", "?"),
        round(float(top.get("share", 0.0)) * 100))


# Arm from the environment at import: the knob rides the launcher env
# contract to every worker (the HOROVOD_FAILPOINTS precedent).
if _env.env_bool(_env.HOROVOD_PROFILE):
    configure(enabled=True)
