"""``horovod_tpu.tensorflow.keras`` — alias of the Keras binding bound
to ``tf.keras`` (reference: horovod/tensorflow/keras/__init__.py).
With TF ≥ 2.16 ``tf.keras`` *is* Keras 3, so the shared implementation
is identical.
"""

from ...keras import *            # noqa: F401,F403
from ...keras import (DistributedOptimizer, broadcast_variables,
                      broadcast_model, allreduce, allgather, broadcast,
                      load_model, callbacks, elastic)  # noqa: F401
