"""In-graph TF collectives: the compiled path for ``tf.function``.

The reference's TF binding is a native AsyncOpKernel that keeps
collectives inside the executed graph (reference:
tensorflow/mpi_ops.cc:374-428 HorovodAllreduceOp).  The TPU-native
equivalent here lowers ``hvd.allreduce``/``allgather``/``broadcast``/
``reducescatter`` inside a traced ``tf.function`` to TensorFlow's own
collective ops (``CollectiveReduceV2`` et al.) over a gRPC worker
cluster wired from the launcher env contract — no per-step
``tf.py_function`` host hop, so the whole train step stays one
compiled graph.

Constraints inherited from TF:

- The collective context must be enabled BEFORE any TF op runs
  (enabling re-initializes the eager context and invalidates existing
  tensors/variables).  ``horovod_tpu.tensorflow.init()`` does it
  automatically when the TF context is still fresh; otherwise call
  :func:`enable_graph_collectives` right after ``hvd.init()`` and
  before building the model, or traced ops fall back to
  ``tf.py_function``.
- Instance keys are assigned in trace order, which must match across
  ranks — the same SPMD program-order contract TF's own
  MultiWorkerMirroredStrategy relies on.  The eager path (negotiated,
  order-independent) is unaffected.
"""

import hashlib
import logging
import os
import socket
import threading

import tensorflow as tf

from ..common import basics
from ..common import env as env_mod
from ..common.basics import (Adasum, Average, Max, Min, Product, Sum,
                             global_process_set)

logger = logging.getLogger("horovod_tpu.tensorflow")

_MERGE_FINAL = {
    Sum: ("Add", "Id"),
    Average: ("Add", "Div"),
    Min: ("Min", "Id"),
    Max: ("Max", "Id"),
    Product: ("Mul", "Id"),
}

# Dtypes TF's CPU collective kernels accept.
_SUPPORTED_DTYPES = (tf.float16, tf.bfloat16, tf.float32, tf.float64,
                     tf.int32, tf.int64)


class _GraphCollectives:
    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._failed = False
        self._instance_key = 1000
        self._group_keys = {}          # tuple(ranks) -> group key
        self._next_group_key = 2
        self.timeout = env_mod.env_float(
            "HOROVOD_TF_COLLECTIVE_TIMEOUT", 0.0)
        # Read once: the kill switch participates in the enable vote,
        # so a rank-asymmetric setting degrades every rank to
        # py_function instead of deadlocking graph ranks against
        # py_function ranks.
        self.env_enabled = env_mod.env_str(
            "HOROVOD_TF_GRAPH_COLLECTIVES", "1").strip().lower() \
            not in ("0", "false", "off")
        # Debug: trace-time key-agreement verification (see key_check).
        self.key_check_enabled = env_mod.env_bool(
            "HOROVOD_TF_COLLECTIVE_KEY_CHECK")
        self._check_seq = 0
        self._key_hash = ""

    def effective_timeout(self) -> float:
        # A peer dying right before a collective can leave the
        # survivors waiting forever (no connection reset to unblock
        # them); elastic needs a bounded wait so the retry loop gets
        # control.  Evaluated per trace (not snapshotted) for the same
        # reason as elastic_graph below.
        if self.timeout:
            return self.timeout
        return 30.0 if self.elastic_graph else 0.0

    @property
    def elastic_graph(self) -> bool:
        """Opt-in elastic mode: graph collectives survive a resize by
        a FULL TF context reset + cluster re-formation on every
        elastic reset (see reset_graph_collectives).  Opt-in because
        the context reset invalidates all live TF objects — user code
        must rebuild model/functions in on_reset (State.rebuild
        re-points the snapshots).  Read per call, not snapshotted at
        import: programs commonly set the env var from their own CLI
        flags after this module is already imported."""
        return env_mod.env_bool("HOROVOD_TF_ELASTIC_GRAPH")

    # -- lifecycle -------------------------------------------------------
    def enable(self) -> bool:
        """Collective call: every rank of the global process set must
        enter (the feasibility vote and address exchange ride the eager
        control plane)."""
        with self._lock:
            if self._enabled:
                return True
            if self._failed:
                return False
            try:
                self._do_enable()
                self._enabled = True
            except Exception as e:
                self._failed = True
                logger.warning(
                    "TF graph collectives unavailable (%s); traced "
                    "collectives fall back to tf.py_function", e)
            return self._enabled

    def _do_enable(self):
        from tensorflow.python.eager import context
        from tensorflow.core.protobuf import (cluster_pb2, config_pb2,
                                              tensorflow_server_pb2)
        from ..runner.http_server import find_ports
        from ..jax import allgather_object

        size, rank = basics.size(), basics.rank()
        if size == 1:
            raise RuntimeError("single process")
        if basics._state().knobs.elastic and not self.elastic_graph:
            raise RuntimeError(
                "graph collectives are incompatible with elastic runs "
                "(group sizes are baked into traced graphs); set "
                "HOROVOD_TF_ELASTIC_GRAPH=1 to opt into context-reset "
                "re-formation on resize (model must be rebuilt in "
                "on_reset)")
        # The enable decision must be unanimous: a rank whose TF
        # context is already live cannot join the cluster (enabling
        # would invalidate its existing tensors), a rank with the kill
        # switch set must not be left behind on py_function, and a
        # split decision would deadlock graph-collective ranks against
        # py_function ranks. One control-plane round settles it.
        local_ok = (self.env_enabled
                    and context.context()._context_handle is None)
        votes = allgather_object(bool(local_ok),
                                 name="tf_graph_collectives.vote")
        if not all(votes):
            raise RuntimeError(
                f"graph collectives vetoed by rank(s) "
                f"{[i for i, v in enumerate(votes) if not v]} (TF "
                "context already initialized there, or "
                "HOROVOD_TF_GRAPH_COLLECTIVES=0); call "
                "enable_graph_collectives() before any TF op")
        (port,) = find_ports(1)
        # The cluster spec is exchanged over the eager control plane
        # (negotiated allgather), so it works under any launcher.
        addrs = allgather_object(f"{self._my_ip()}:{port}",
                                 name="tf_graph_collectives.addrs")
        cluster = cluster_pb2.ClusterDef()
        job = cluster.job.add()
        job.name = "worker"
        for i, addr in enumerate(addrs):
            job.tasks[i] = addr
        cfg = config_pb2.ConfigProto()
        cfg.experimental.collective_group_leader = \
            "/job:worker/replica:0/task:0"
        server_def = tensorflow_server_pb2.ServerDef(
            cluster=cluster, job_name="worker", task_index=rank,
            protocol="grpc", port=port, default_session_config=cfg)
        # The local bring-up can still fail after a passing vote (e.g.
        # the gRPC port was snatched between find_ports and bind), so
        # the OUTCOME is agreed too: unless every rank succeeded, all
        # ranks use the py_function path.
        try:
            context.context().enable_collective_ops(server_def)
            ok = True
        except Exception as e:
            logger.warning("collective-ops bring-up failed locally: %s",
                           e)
            ok = False
        outcomes = allgather_object(ok,
                                    name="tf_graph_collectives.outcome")
        if not all(outcomes):
            raise RuntimeError(
                f"collective-ops bring-up failed on rank(s) "
                f"{[i for i, v in enumerate(outcomes) if not v]}; all "
                "ranks fall back to the py_function path")
        self.device = f"/job:worker/replica:0/task:{rank}/device:CPU:0"
        # Fail-fast wiring: when the control plane dies mid-run (a
        # peer hard-died in an elastic resize), abort in-flight TF
        # collectives so the user thread unwinds NOW instead of
        # riding out timeout_seconds while the rest of the world
        # tears down (a slow unwind here is what lets the jax
        # coordination leader disappear under a still-attached
        # client, which is process-fatal).
        runtime = getattr(basics._state(), "runtime", None)
        if runtime is not None and hasattr(runtime,
                                           "add_fatal_listener"):
            def abort_tf_collectives(err):
                try:
                    context.context().abort_collective_ops(
                        14,  # UNAVAILABLE
                        f"horovod control plane failed: {err}")
                except Exception:
                    pass
            runtime.add_fatal_listener(abort_tf_collectives)

    @staticmethod
    def _my_ip() -> str:
        ctrl = env_mod.env_str_opt("HOROVOD_CONTROLLER_ADDR")
        if ctrl:
            host, _, port = ctrl.rpartition(":")
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.connect((host, int(port)))
                ip = s.getsockname()[0]
                s.close()
                return ip
            except OSError:
                pass
        return "127.0.0.1"

    # -- key management --------------------------------------------------
    def usable(self, process_set, dtype=None) -> bool:
        if not self.env_enabled:
            return False
        # Elastic runs resize the world; traced graphs bake group_size
        # and the gRPC cluster at trace time, so reused graphs would
        # execute stale collectives after a resize. Elastic stays on
        # the execution-time (py_function) path — unless the user
        # opted into context-reset re-formation
        # (HOROVOD_TF_ELASTIC_GRAPH=1, see reset_graph_collectives).
        if basics.is_initialized() and basics._state().knobs.elastic \
                and not self.elastic_graph:
            return False
        if dtype is not None and tf.as_dtype(dtype) not in _SUPPORTED_DTYPES:
            return False
        if basics.size() == 1:
            return True     # identity lowering, no cluster needed
        if not process_set.included(basics.rank()):
            return False
        # No lazy enabling here: usable() is called at trace time, when
        # ranks may disagree (non-members of a process set, contexts in
        # different states) — a blocking collective enable from here
        # could deadlock. The cluster comes up in init() /
        # enable_graph_collectives(), which are documented collective
        # calls.
        return self._enabled

    def group(self, process_set):
        """(group_key, group_size) for a process set."""
        if process_set is global_process_set or \
                process_set.ranks is None:
            return 1, basics.size()
        key = tuple(sorted(process_set.ranks))
        with self._lock:
            if key not in self._group_keys:
                self._group_keys[key] = self._next_group_key
                self._next_group_key += 1
            return self._group_keys[key], len(key)

    def next_instance_key(self) -> int:
        # Trace-order assignment; identical across ranks tracing the
        # same program (see module docstring).
        with self._lock:
            self._instance_key += 1
            return self._instance_key

    def key_check(self, kind: str, instance_key: int, group_key: int,
                  dtype, shape, name):
        """Trace-time divergence detector (debug knob
        ``HOROVOD_TF_COLLECTIVE_KEY_CHECK=1``).

        Instance keys are assigned in trace order; rank-divergent
        conditional tracing silently pairs DIFFERENT collectives under
        the SAME key and deadlocks (or corrupts) at execution time.
        With the knob set, every emitted collective allgathers a
        record of (kind, instance key, group key, dtype, shape) plus
        a rolling hash of the whole emission history over the eager
        control plane, and raises at the FIRST divergent op — naming
        it — instead of hanging in TF's collective executor.  The
        reference does the analogous validation on the coordinator
        (controller.cc:471-748 shape/dtype mismatch -> ERROR
        response).

        The exchange is sequence-numbered (not keyed by instance key)
        so ranks that disagree on keys still meet in the same
        negotiation round.  If a rank stops emitting entirely, the
        other ranks' next exchange parks in the negotiated allgather,
        where the stall inspector attributes the missing rank — still
        strictly better than a bare TF deadlock.  Trace-time only:
        zero cost at step time.
        """
        if not self.key_check_enabled or basics.size() == 1:
            return
        from ..jax import allgather_object

        with self._lock:
            seq = self._check_seq
            self._check_seq += 1
            rec = (kind, instance_key, group_key, str(dtype),
                   str(tuple(shape) if shape is not None else None),
                   str(name or ""))
            self._key_hash = hashlib.sha256(
                (self._key_hash + repr(rec)).encode()).hexdigest()
            payload = (self._key_hash, rec)
        views = allgather_object(
            payload, name=f"tf_graph_collectives.keycheck.{seq}")
        # Equality is judged on the RECORDS (each emission is checked
        # in sequence, so the first divergent op trips here); the
        # rolling hash is carried as context only — judging on it too
        # would poison every later, agreeing trace after a detected
        # divergence.
        if all(v[1] == views[0][1] for v in views):
            return
        lines = [
            f"  rank {i}: {'DIVERGED ' if v[1] != views[0][1] else ''}"
            f"{v[1][0]} instance_key={v[1][1]} group_key={v[1][2]} "
            f"dtype={v[1][3]} shape={v[1][4]} name={v[1][5]} "
            f"history={v[0][:12]}" for i, v in enumerate(views)]
        raise RuntimeError(
            "rank-divergent tf.function tracing detected at traced "
            f"collective #{seq} (this rank: {kind} of {name or rec}) "
            "— ranks are emitting different collective sequences, "
            "which would deadlock at execution time. Make traced "
            "control flow identical across ranks (no rank-dependent "
            "conditionals around hvd ops).\n" + "\n".join(lines))


_ctx = _GraphCollectives()


def enable_graph_collectives() -> bool:
    """Set up TF's collective-ops cluster so hvd ops inside
    ``tf.function`` compile to in-graph collectives.  Collective call:
    every rank must enter, before the first TF op of the process.
    Returns False (with a warning) when unavailable."""
    if basics.size() == 1:
        return True
    return _ctx.enable()


def reset_graph_collectives() -> bool:
    """Re-form the collective cluster at the CURRENT world size after
    an elastic resize.  Collective call: every post-resize rank must
    enter (the elastic reset path does this automatically under
    ``HOROVOD_TF_ELASTIC_GRAPH=1``).

    TF refuses to shrink a live cluster (``update_server_def``
    rejects removed tasks), so survival goes through a FULL eager
    context reset: every live TF tensor/variable/function dies, a
    fresh context enables collective ops against the new cluster, and
    user code rebuilds its model/functions in ``on_reset`` (elastic
    State snapshots are numpy and survive; ``State.rebuild`` re-points
    them at the fresh objects).  The reference never solved this —
    its elastic TF path re-creates graphs per reset too (exec-time
    size ops, tensorflow/mpi_ops.py:327-391); the context reset is
    the TF2-collective-ops equivalent."""
    global _ctx
    from tensorflow.python.eager import context
    if context.context()._context_handle is not None:
        context._reset_context()
    _ctx = _GraphCollectives()
    if basics.size() == 1:
        return True
    return _ctx.enable()


def reset_graph_collectives_for_testing():
    global _ctx
    _ctx = _GraphCollectives()


# ---------------------------------------------------------------------------
# graph-mode emitters (callers guarantee usable() returned True)
# ---------------------------------------------------------------------------

def _scaled(tensor, factor):
    if factor == 1.0:
        return tensor
    return tensor * tf.cast(factor, tensor.dtype)


def allreduce_graph(tensor, op, prescale_factor, postscale_factor,
                    process_set):
    if op not in _MERGE_FINAL:
        raise NotImplementedError(
            f"op {op} has no in-graph lowering (Adasum stays on the "
            "negotiated eager path)")
    group_key, group_size = _ctx.group(process_set)
    tensor = _scaled(tensor, prescale_factor)
    if group_size == 1:
        return _scaled(tensor, postscale_factor)
    merge_op, final_op = _MERGE_FINAL[op]
    ikey = _ctx.next_instance_key()
    _ctx.key_check("allreduce", ikey, group_key, tensor.dtype,
                   tensor.shape, getattr(tensor, "name", None))
    out = tf.raw_ops.CollectiveReduceV2(
        input=tensor, group_size=group_size, group_key=group_key,
        instance_key=ikey, ordering_token=[],
        merge_op=merge_op, final_op=final_op,
        communication_hint="ring", timeout_seconds=_ctx.effective_timeout())
    return _scaled(out, postscale_factor)


def grouped_allreduce_graph(tensors, op, prescale_factor,
                            postscale_factor, process_set):
    return [allreduce_graph(t, op, prescale_factor, postscale_factor,
                            process_set) for t in tensors]


def allgather_graph(tensor, process_set):
    group_key, group_size = _ctx.group(process_set)
    if group_size == 1:
        return tf.identity(tensor)
    ikey = _ctx.next_instance_key()
    _ctx.key_check("allgather", ikey, group_key, tensor.dtype,
                   tensor.shape, getattr(tensor, "name", None))
    return tf.raw_ops.CollectiveGatherV2(
        input=tensor, group_size=group_size, group_key=group_key,
        instance_key=ikey, ordering_token=[],
        communication_hint="ring", timeout_seconds=_ctx.effective_timeout())


def broadcast_graph(tensor, root_rank, process_set):
    group_key, group_size = _ctx.group(process_set)
    if group_size == 1:
        return tf.identity(tensor)
    ikey = _ctx.next_instance_key()
    _ctx.key_check("broadcast", ikey, group_key, tensor.dtype,
                   tensor.shape, getattr(tensor, "name", None))
    kwargs = dict(group_size=group_size, group_key=group_key,
                  instance_key=ikey,
                  communication_hint="ring",
                  timeout_seconds=_ctx.effective_timeout())
    if basics.rank() == root_rank:
        return tf.raw_ops.CollectiveBcastSendV2(input=tensor, **kwargs)
    return tf.raw_ops.CollectiveBcastRecvV2(
        T=tensor.dtype, shape=tf.shape(tensor), **kwargs)


def reducescatter_graph(tensor, op, process_set):
    if op not in (Sum, Average):
        raise NotImplementedError("reducescatter supports Sum/Average")
    group_key, group_size = _ctx.group(process_set)
    if group_size == 1:
        return tf.identity(tensor)
    merge_op, final_op = _MERGE_FINAL[op]
    ikey = _ctx.next_instance_key()
    _ctx.key_check("reducescatter", ikey, group_key, tensor.dtype,
                   tensor.shape, getattr(tensor, "name", None))
    return tf.raw_ops.CollectiveReduceScatterV2(
        input=tensor, group_size=group_size, group_key=group_key,
        instance_key=ikey, ordering_token=[],
        merge_op=merge_op, final_op=final_op,
        communication_hint="ring", timeout_seconds=_ctx.effective_timeout())
