"""TensorFlow 2 framework binding.

The compatibility surface of the reference's ``horovod.tensorflow``
(reference: tensorflow/__init__.py — allreduce with the IndexedSlices
sparse path :54-155, grouped_allreduce :156, broadcast_variables :263,
_make_allreduce_grads_fn :334-381, DistributedOptimizer :568-689,
DistributedGradientTape :691+; op wrappers tensorflow/mpi_ops.py).

TPU-native design note: the hot path of this framework is JAX/XLA
(:mod:`horovod_tpu.jax`, :mod:`horovod_tpu.training`).  The TF binding
has two data paths: eager ops stage tensors through host memory into
the negotiated background runtime (the analog of the reference's
``*CudaOnCPU`` staged variants, torch/mpi_ops_v2.cc:93-127), while ops
traced inside ``tf.function`` lower to TensorFlow's native in-graph
collectives (:mod:`.graph_ops` — no per-step ``tf.py_function`` host
hop, the analog of the reference's AsyncOpKernels,
tensorflow/mpi_ops.cc:374-428), falling back to ``tf.py_function``
when the collective cluster is unavailable.  The ``*_op`` scalar
queries stay execution-time reads, which is what elastic graph reuse
needs (reference tensorflow/mpi_ops.py:327-391).
"""

import warnings
from typing import List, Optional

import numpy as np
import tensorflow as tf

from ..common import basics
from ..common.basics import (Adasum, Average, Max, Min, Product, Sum,
                             ProcessSet, global_process_set, init,
                             is_homogeneous, is_initialized, local_rank,
                             local_size, cross_rank, cross_size,
                             mpi_built, mpi_enabled, gloo_built,
                             gloo_enabled, nccl_built, rank, shutdown,
                             size, start_timeline, stop_timeline)
from .. import ops as _ops
from ..ops.compression import Compression
from ..ops.eager import _resolve_op
from . import graph_ops as _graph
from .graph_ops import (enable_graph_collectives,
                        reset_graph_collectives)

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "is_initialized", "is_homogeneous",
    "mpi_built", "mpi_enabled", "gloo_built", "gloo_enabled",
    "nccl_built", "start_timeline", "stop_timeline",
    "Average", "Sum", "Adasum", "Min", "Max", "Product", "Compression",
    "ProcessSet", "global_process_set",
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "alltoall", "reducescatter", "join", "barrier",
    "size_op", "rank_op", "local_size_op", "local_rank_op",
    "process_set_included_op",
    "broadcast_variables", "broadcast_global_variables",
    "broadcast_object", "allgather_object",
    "DistributedOptimizer", "DistributedGradientTape",
    "SyncBatchNormalization", "elastic", "enable_graph_collectives",
    "reset_graph_collectives",
]


_basics_init = init


def init(comm=None, process_sets=None):
    """hvd.init plus best-effort TF graph-collective setup. The enable
    attempt is unconditional on every rank (a unanimous-feasibility
    vote inside decides; see graph_ops) so ranks cannot diverge between
    the compiled and py_function paths."""
    result = _basics_init(comm=comm, process_sets=process_sets)
    if basics.size() > 1:
        enable_graph_collectives()
    return result


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return tensor.numpy() if hasattr(tensor, "numpy") \
        else np.asarray(tensor)


def _eager(tensor) -> bool:
    return not isinstance(tensor, tf.Tensor) or \
        tf.executing_eagerly() or hasattr(tensor, "numpy")


def _run_op(fn, inputs, output_dtype):
    """Run ``fn(np_arrays...) -> np_array`` eagerly or as a graph
    py_function node."""
    if all(_eager(t) for t in inputs):
        return tf.convert_to_tensor(fn(*[_to_numpy(t) for t in inputs]))
    return tf.py_function(
        lambda *ts: fn(*[t.numpy() for t in ts]), inputs, output_dtype)


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor=1.0, postscale_factor=1.0, name=None,
              process_set=global_process_set):
    """Allreduce a tf.Tensor or tf.IndexedSlices across ranks.

    IndexedSlices with Average/Sum use the allgather sparse path
    (reference: tensorflow/__init__.py:54-155)."""
    if isinstance(tensor, tf.IndexedSlices):
        if op not in (None, Average, Sum):
            raise NotImplementedError(
                "IndexedSlices allreduce supports Average and Sum only")
        if op is not None and average is not None:
            raise ValueError("Cannot specify both 'op' and deprecated "
                             "'average' arguments.")
        do_average = (op == Average) if op is not None \
            else (average is None or average)
        values = allgather(tensor.values, process_set=process_set)
        indices = allgather(tensor.indices, process_set=process_set)
        if do_average:
            values = tf.cast(values, tensor.values.dtype) / \
                tf.cast(process_set.size(), tensor.values.dtype)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    def _fn(arr):
        c, ctx = compression.compress(arr)
        out = _ops.allreduce(c, average=average, op=op, name=name,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set)
        return np.asarray(compression.decompress(out, ctx))

    if not _eager(tensor) and compression is Compression.none:
        resolved = _resolve_op(op, average)
        if resolved in _graph._MERGE_FINAL and \
                _graph._ctx.usable(process_set, tensor.dtype):
            return _graph.allreduce_graph(
                tensor, resolved, prescale_factor, postscale_factor,
                process_set)
    return _run_op(_fn, [tensor],
                   tensor.dtype if hasattr(tensor, "dtype") else None)


def grouped_allreduce(tensors, average=None, compression=Compression.none,
                      op=None, prescale_factor=1.0, postscale_factor=1.0,
                      process_set=global_process_set):
    if not tensors:
        return tensors

    def _fn(*arrs):
        compressed, ctxs = [], []
        for a in arrs:
            c, ctx = compression.compress(a)
            compressed.append(c)
            ctxs.append(ctx)
        outs = _ops.grouped_allreduce(
            compressed, average=average, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set)
        return [np.asarray(compression.decompress(o, ctx))
                for o, ctx in zip(outs, ctxs)]

    if all(_eager(t) for t in tensors):
        outs = _fn(*[_to_numpy(t) for t in tensors])
        return [tf.convert_to_tensor(o) for o in outs]
    resolved = _resolve_op(op, average)
    if compression is Compression.none and \
            resolved in _graph._MERGE_FINAL and all(
            _graph._ctx.usable(process_set, t.dtype) for t in tensors):
        return _graph.grouped_allreduce_graph(
            list(tensors), resolved, prescale_factor, postscale_factor,
            process_set)
    return list(tf.py_function(
        lambda *ts: _fn(*[t.numpy() for t in ts]), list(tensors),
        [t.dtype for t in tensors]))


def allgather(tensor, name=None, process_set=global_process_set):
    # CollectiveGatherV2 requires equal shapes on every rank; the
    # negotiated path supports ragged first dims (xla_ops allgather
    # takes per-rank sizes). A dynamic first dim at trace time (e.g.
    # the IndexedSlices sparse path, where slice counts are
    # data-dependent) therefore stays on the negotiated path.
    static_dim0 = (getattr(tensor, "shape", None) is not None and
                   tensor.shape.rank and tensor.shape[0] is not None)
    if not _eager(tensor) and static_dim0 and \
            _graph._ctx.usable(process_set, tensor.dtype):
        return _graph.allgather_graph(tensor, process_set)
    return _run_op(
        lambda a: np.asarray(_ops.allgather(a, name=name,
                                            process_set=process_set)),
        [tensor], tensor.dtype if hasattr(tensor, "dtype") else None)


def broadcast(tensor, root_rank, name=None,
              process_set=global_process_set):
    if not _eager(tensor) and _graph._ctx.usable(process_set,
                                                 tensor.dtype):
        return _graph.broadcast_graph(tensor, root_rank, process_set)
    return _run_op(
        lambda a: np.asarray(_ops.broadcast(a, root_rank, name=name,
                                            process_set=process_set)),
        [tensor], tensor.dtype if hasattr(tensor, "dtype") else None)


def alltoall(tensor, splits=None, name=None,
             process_set=global_process_set):
    if splits is None:
        return _run_op(
            lambda a: np.asarray(_ops.alltoall(a, name=name,
                                               process_set=process_set)),
            [tensor], tensor.dtype if hasattr(tensor, "dtype") else None)
    out, recv = _ops.alltoall(_to_numpy(tensor), _to_numpy(splits),
                              name=name, process_set=process_set)
    return tf.convert_to_tensor(np.asarray(out)), \
        tf.convert_to_tensor(np.asarray(recv))


def reducescatter(tensor, op=None, name=None,
                  process_set=global_process_set):
    # CollectiveReduceScatterV2 needs dim 0 divisible by group size;
    # the eager/XLA path implements the uneven-split convention, so
    # only lower when divisibility is statically certain.
    dim0 = tensor.shape[0] if tensor.shape.rank else None
    if not _eager(tensor) and op in (None, Sum, Average) and \
            dim0 is not None and \
            dim0 % max(process_set.size(), 1) == 0 and \
            _graph._ctx.usable(process_set, tensor.dtype):
        return _graph.reducescatter_graph(tensor, op or Sum, process_set)
    return _run_op(
        lambda a: np.asarray(_ops.reducescatter(a, name=name, op=op,
                                                process_set=process_set)),
        [tensor], tensor.dtype if hasattr(tensor, "dtype") else None)


def join():
    return _ops.join()


def barrier(process_set=global_process_set):
    return _ops.barrier(process_set)


# ---------------------------------------------------------------------------
# graph-execution-time scalar ops (reference: tensorflow/mpi_ops.py:327-391
# — values read at execution, not trace, time: required for elastic)
# ---------------------------------------------------------------------------
def size_op(process_set=global_process_set, name=None):
    return tf.py_function(lambda: process_set.size(), [], tf.int32)


def rank_op(name=None):
    return tf.py_function(lambda: basics.rank(), [], tf.int32)


def local_size_op(name=None):
    return tf.py_function(lambda: basics.local_size(), [], tf.int32)


def local_rank_op(name=None):
    return tf.py_function(lambda: basics.local_rank(), [], tf.int32)


def process_set_included_op(process_set=global_process_set, name=None):
    return tf.py_function(
        lambda: int(process_set.included(basics.rank())), [], tf.int32)


# ---------------------------------------------------------------------------
# variable broadcast / object collectives
# ---------------------------------------------------------------------------
def broadcast_variables(variables, root_rank: int,
                        process_set=global_process_set):
    """Assign every variable its root_rank value (reference:
    tensorflow/__init__.py:263-330 broadcast_global_variables).

    Works both eagerly and inside a traced ``tf.function`` (the
    reference's TF2 examples call it from the first traced train step):
    traced calls lower through the graph broadcast path (in-graph
    collectives or the py_function fallback)."""
    variables = list(variables)
    if tf.executing_eagerly():
        for i, var in enumerate(variables):
            name = getattr(var, "name", None) or f"bcast_var.{i}"
            value = _ops.broadcast(_to_numpy(var), root_rank,
                                   name=f"bcast/{name}",
                                   process_set=process_set)
            var.assign(np.asarray(value))
        return None
    assigns = []
    for i, var in enumerate(variables):
        name = getattr(var, "name", None) or f"bcast_var.{i}"
        value = broadcast(tf.convert_to_tensor(var), root_rank,
                          name=f"bcast/{name}", process_set=process_set)
        assigns.append(var.assign(value))
    return tf.group(*assigns) if assigns else None


def broadcast_global_variables(root_rank: int):
    if tf.compat.v1.executing_eagerly_outside_functions():
        raise RuntimeError(
            "broadcast_global_variables is graph-mode only; use "
            "broadcast_variables(model.variables, root_rank) in TF2.")
    return broadcast_variables(tf.compat.v1.global_variables(), root_rank)


def broadcast_object(obj=None, root_rank=0, name="broadcast_object",
                     process_set=global_process_set):
    from ..jax import broadcast_object as _bo
    return _bo(obj, root_rank, name=name, process_set=process_set)


def allgather_object(obj, name="allgather_object",
                     process_set=global_process_set):
    from ..jax import allgather_object as _ao
    return _ao(obj, name=name, process_set=process_set)


# ---------------------------------------------------------------------------
# gradient reduction (reference: _make_allreduce_grads_fn,
# tensorflow/__init__.py:334-381)
# ---------------------------------------------------------------------------
def _make_allreduce_grads_fn(name, device_dense, device_sparse,
                             compression, sparse_as_dense, op,
                             gradient_predivide_factor=1.0,
                             groups=None,
                             process_set=global_process_set):
    def _scales():
        # Resolved at call time, not wrap time: size() may change
        # across elastic resets (reference reads size at execution
        # time, tensorflow/mpi_ops.py:327-391).
        if op == Average:
            # Split Average into pre/postscale around Sum so predivide
            # composes exactly (reference tensorflow/__init__.py:337-344).
            return (1.0 / gradient_predivide_factor,
                    gradient_predivide_factor / process_set.size(), Sum)
        return 1.0, 1.0, op

    def allreduce_grads(grads, vars=None):
        prescale, postscale, reduce_op = _scales()
        processed = []
        for grad in grads:
            if grad is not None and sparse_as_dense and \
                    isinstance(grad, tf.IndexedSlices):
                grad = tf.convert_to_tensor(grad)
            processed.append(grad)
        index = [i for i, g in enumerate(processed) if g is not None]
        dense = [processed[i] for i in index]
        if groups is not None and groups > 1:
            reduced = []
            for i in range(0, len(dense), max(1, len(dense) // groups)):
                reduced.extend(grouped_allreduce(
                    dense[i:i + max(1, len(dense) // groups)],
                    compression=compression, op=reduce_op,
                    prescale_factor=prescale, postscale_factor=postscale,
                    process_set=process_set))
        else:
            reduced = grouped_allreduce(
                dense, compression=compression, op=reduce_op,
                prescale_factor=prescale, postscale_factor=postscale,
                process_set=process_set) if dense else []
        out = list(processed)
        for i, g in zip(index, reduced):
            out[i] = g
        return out

    return allreduce_grads


class DistributedGradientTape:
    """GradientTape wrapper whose ``gradient()`` allreduces the result
    (reference: tensorflow/__init__.py:691+).  Pure delegation — NOT a
    tf.GradientTape subclass, so the C-level tape state stays owned by
    the wrapped tape."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average, gradient_predivide_factor=1.0,
                 num_groups=None, process_set=global_process_set):
        self._tape = gradtape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", device_dense, device_sparse,
            compression, sparse_as_dense, op, gradient_predivide_factor,
            num_groups, process_set)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        return self._tape.__exit__(exc_type, exc, tb)

    def __getattr__(self, item):
        return getattr(self.__dict__["_tape"], item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return self._allreduce_grads(grads, sources)


def DistributedOptimizer(optimizer, name=None, device_dense="",
                         device_sparse="", compression=Compression.none,
                         sparse_as_dense=False,
                         backward_passes_per_step=1, op=Average,
                         gradient_predivide_factor=1.0,
                         average_aggregated_gradients=False,
                         num_groups=None,
                         process_set=global_process_set):
    """Wrap a Keras optimizer so apply_gradients() first allreduces the
    gradients (reference: tensorflow/__init__.py:568-689 /
    _keras/__init__.py create_distributed_optimizer)."""
    from .._keras import create_distributed_optimizer
    return create_distributed_optimizer(
        optimizer, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense,
        backward_passes_per_step=backward_passes_per_step, op=op,
        gradient_predivide_factor=gradient_predivide_factor,
        average_aggregated_gradients=average_aggregated_gradients,
        num_groups=num_groups, process_set=process_set,
        make_allreduce_grads_fn=_make_allreduce_grads_fn)


from .sync_batch_norm import SyncBatchNormalization  # noqa: E402
from . import elastic  # noqa: E402
