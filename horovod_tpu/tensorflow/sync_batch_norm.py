"""Synchronized batch normalization for Keras/TF.

Reference: tensorflow/sync_batch_norm.py:26-60 — batch moments are
computed across ALL ranks by allreducing the stacked
[mean, mean-of-squares] so every worker normalizes with global batch
statistics (essential when per-worker batches are small).

Implemented as a Keras layer on the TensorFlow backend: local moments →
one stacked-moment allreduce (Average, via the binding's graph-aware
op, so tf.function traces get a tf.py_function node) → global mean/var
→ normalize.  Inference uses the moving statistics like plain
BatchNormalization.
"""

import numpy as np
import keras
from keras import ops as K

from ..common import basics
from ..common.basics import Average, global_process_set


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """Drop-in BatchNormalization with cross-rank batch statistics.
    Requires the TensorFlow Keras backend (the JAX-backend equivalent
    is horovod_tpu.parallel's in-graph statistics)."""

    def __init__(self, process_set=global_process_set, **kwargs):
        super().__init__(**kwargs)
        self._process_set = process_set

    def call(self, inputs, training=None, mask=None):
        if self._process_set.size() == 1 or training is None:
            return super().call(inputs, training=training, mask=mask)
        # ``training`` may be a symbolic tensor under tf.function
        # tracing; ``not training`` would then branch on the Python
        # truthiness of the tensor object (always True) instead of its
        # value.  Resolve a static value when possible, else tf.cond.
        if isinstance(training, (bool, int, np.bool_)):
            static_training = bool(training)
        else:
            import tensorflow as tf
            static_training = tf.get_static_value(training)
            if static_training is None:
                return tf.cond(
                    tf.cast(training, tf.bool),
                    lambda: self._sync_call(inputs, mask),
                    lambda: super(SyncBatchNormalization, self).call(
                        inputs, training=False, mask=mask))
            static_training = bool(static_training)
        if not static_training:
            return super().call(inputs, training=False, mask=mask)
        return self._sync_call(inputs, mask)

    def _sync_call(self, inputs, mask=None):
        if keras.backend.backend() != "tensorflow":
            raise RuntimeError(
                "horovod_tpu.tensorflow.SyncBatchNormalization requires "
                "the TensorFlow Keras backend; on JAX use the in-graph "
                "mesh statistics (horovod_tpu.parallel).")
        from . import allreduce as tf_allreduce

        x = K.convert_to_tensor(inputs)
        ndim = len(x.shape)
        axis = self.axis if self.axis >= 0 else ndim + self.axis
        reduce_axes = [i for i in range(ndim) if i != axis]

        local_mean = K.mean(x, axis=reduce_axes)
        local_sq_mean = K.mean(K.square(x), axis=reduce_axes)
        # One fused allreduce of the stacked moments (reference
        # stacks mean and mean-of-squares into a single tensor);
        # tf_allreduce handles both eager and tf.function tracing.
        stacked = K.stack([local_mean, local_sq_mean])
        reduced = tf_allreduce(stacked, op=Average,
                               name=f"sync_bn/{self.name}",
                               process_set=self._process_set)
        mean = reduced[0]
        var = reduced[1] - K.square(mean)

        # Update moving statistics exactly like the base layer.
        momentum = K.cast(self.momentum, mean.dtype)
        self.moving_mean.assign(self.moving_mean * momentum +
                                mean * (1.0 - momentum))
        self.moving_variance.assign(self.moving_variance * momentum +
                                    var * (1.0 - momentum))

        shape = [1] * ndim
        shape[axis] = x.shape[axis]
        mean = K.reshape(mean, shape)
        var = K.reshape(var, shape)
        out = (x - mean) / K.sqrt(var + self.epsilon)
        if self.scale:
            out = out * K.reshape(self.gamma, shape)
        if self.center:
            out = out + K.reshape(self.beta, shape)
        return out
