"""Elastic state for TensorFlow (reference: tensorflow/elastic.py:31,91
— ``run`` wrapper and ``TensorFlowKerasState``).
"""

import numpy as np

from ..common import basics
from ..common.elastic import ObjectState, run_fn
from .. import ops as _ops
from ..keras.elastic import KerasState as TensorFlowKerasState  # noqa: F401


def _reset():
    basics.shutdown()
    basics.init()


def run(func):
    """Elastic retry-loop decorator (reference: tensorflow/elastic.py
    run)."""
    return run_fn(func, _reset)


class TensorFlowState(ObjectState):
    """Snapshot/restore/sync for a collection of tf.Variables
    (reference: tensorflow/elastic.py TensorFlowState)."""

    def __init__(self, variables=None, **kwargs):
        self.variables = list(variables or [])
        self._saved = None

        def bcast(obj):
            from ..jax import broadcast_object
            return broadcast_object(obj, 0, name="tf_elastic")

        super().__init__(bcast_object=bcast, get_rank=basics.rank,
                         **kwargs)
        self.save()

    def save(self):
        self._saved = [np.array(v) for v in self.variables]
        super().save()

    def restore(self):
        if self._saved is not None:
            for var, w in zip(self.variables, self._saved):
                var.assign(w)
        super().restore()

    def sync(self):
        for i, var in enumerate(self.variables):
            var.assign(np.asarray(_ops.broadcast(
                np.array(var), 0, name=f"tf_elastic/var.{i}")))
        self._saved = [np.array(v) for v in self.variables]
        super().sync()
