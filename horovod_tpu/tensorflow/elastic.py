"""Elastic state for TensorFlow (reference: tensorflow/elastic.py:31,91
— ``run`` wrapper and ``TensorFlowKerasState``).
"""

import numpy as np

from ..common import basics
from ..common.elastic import ObjectState, run_fn
from .. import ops as _ops
from ..keras.elastic import KerasState as TensorFlowKerasState  # noqa: F401


def _reset():
    basics.shutdown()
    basics.init()
    from . import graph_ops
    if graph_ops._ctx.elastic_graph:
        # Opt-in (HOROVOD_TF_ELASTIC_GRAPH=1): re-form the collective
        # cluster at the new world size via a full TF context reset.
        # Model/functions must be rebuilt in on_reset; see
        # reset_graph_collectives.
        graph_ops.reset_graph_collectives()


def run(func):
    """Elastic retry-loop decorator (reference: tensorflow/elastic.py
    run).  TF connection-class errors (a peer dying inside an
    in-graph CollectiveReduceV2 surfaces as UnavailableError, not
    HorovodInternalError) are translated so the retry loop can
    restore/reset — the eager path's op wrappers already raise
    HorovodInternalError themselves."""
    def tf_guard(state, *args, **kwargs):
        import tensorflow as tf
        from ..common.exceptions import HorovodInternalError
        try:
            return func(state, *args, **kwargs)
        except (tf.errors.UnavailableError, tf.errors.AbortedError,
                tf.errors.CancelledError,
                tf.errors.DeadlineExceededError) as e:
            # Distributed-failure codes only: Unavailable/Aborted are
            # what a dead peer or an abort_collective_ops produces,
            # Cancelled is what subsequent ops on the aborted executor
            # produce, DeadlineExceeded is the collective timeout.
            # Deterministic local failures (InternalError from a
            # compiler bug, InvalidArgument, ...) must SURFACE, not
            # loop the retry forever.
            raise HorovodInternalError(str(e)) from e
    return run_fn(tf_guard, _reset)


class TensorFlowState(ObjectState):
    """Snapshot/restore/sync for a collection of tf.Variables
    (reference: tensorflow/elastic.py TensorFlowState)."""

    def __init__(self, variables=None, **kwargs):
        self.variables = list(variables or [])
        self._saved = None

        def bcast(obj):
            from ..jax import broadcast_object
            return broadcast_object(obj, 0, name="tf_elastic")

        super().__init__(bcast_object=bcast, get_rank=basics.rank,
                         **kwargs)
        self.save()

    def save(self):
        self._saved = [np.array(v) for v in self.variables]
        super().save()

    def _seed_from_snapshot(self):
        if self._saved is not None:
            for var, w in zip(self.variables, self._saved):
                var.assign(w)

    def restore(self):
        self._seed_from_snapshot()
        super().restore()

    def rebuild(self, variables):
        """Re-point the state at freshly built variables and seed them
        from the last snapshot — for HOROVOD_TF_ELASTIC_GRAPH resets,
        where the TF context reset invalidated the old objects (call
        from on_reset after rebuilding the model)."""
        self.variables = list(variables)
        self._seed_from_snapshot()

    def sync(self):
        for i, var in enumerate(self.variables):
            var.assign(np.asarray(_ops.broadcast(
                np.array(var), 0, name=f"tf_elastic/var.{i}")))
        self._saved = [np.array(v) for v in self.variables]
        super().sync()
