"""Row-sharded embedding tables over the eager alltoall plane.

Exchange protocol for one lookup (three alltoalls, splits piggybacked
on the coordinator response each time):

1. **ids**: each rank sorts its batch's row ids by owning rank
   (stable, so per-owner order is deterministic) and alltoalls the
   sorted ids with per-owner send counts as splits.  Every rank now
   holds the ids its shard must serve, grouped by requesting rank.
2. **rows**: owners gather the requested rows from their local slice
   and alltoall them straight back with the RECEIVED splits — each
   requester gets rows in exactly the order it sent ids, then undoes
   its sort permutation.
3. **grads** (backward): requesters route row gradients with the same
   splits as (1); owners receive them aligned with the ids from (1)
   and scatter-add locally (``np.add.at`` — duplicate ids in a batch
   accumulate, matching dense embedding-gradient semantics).

Ownership is round-robin (``owner = id % size``, ``slot = id //
size``) so skewed id distributions still balance.  All exchanges ride
``hvd.alltoall`` with explicit splits — the validated, recv-splits-
piggybacking path — under per-table tensor names, so 8 ranks issuing
lookups for several tables negotiate them like any other collective
stream.

Touched-row tracking: every local update stamps its slots with a
fresh generation.  ``snapshot_touched()`` / ``durable_items()`` /
``clear_touched()`` give the checkpoint layer the capture → commit →
clear lifecycle: clear only after the save is durable, and a subset
clear forgets only touches from at or before the snapshot — a row
updated while its delta save was in flight, and a failed save's
rows, both stay marked so the next delta still carries them.
"""

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import env as _env
from ..common import metrics
from ..checkpoint.delta import RowDelta, assemble_table

logger = logging.getLogger("horovod_tpu.sparse")

_A2A_OPS = metrics.counter(
    "hvd_sparse_alltoall_ops_total",
    "Alltoall exchanges issued by the sparse embedding engine, by "
    "stage (ids/rows/grads)")
_A2A_BYTES = metrics.counter(
    "hvd_sparse_alltoall_bytes_total",
    "Payload bytes sent by sparse embedding alltoalls, by stage")
_LOOKUP_SECONDS = metrics.histogram(
    "hvd_sparse_lookup_seconds",
    "Wall time of ShardedEmbedding lookup/apply_gradients calls")


def _hvd_rank_size() -> Tuple[int, int]:
    from ..common import basics
    return basics.rank(), basics.size()


def _alltoall(tensor: np.ndarray, splits: np.ndarray, name: str
              ) -> Tuple[np.ndarray, np.ndarray]:
    from ..ops import eager
    out, recv = eager.alltoall(tensor, splits=splits, name=name)
    return np.asarray(out), np.asarray(recv)


class _LookupContext:
    """Routing state one lookup leaves behind for its backward.

    With dedupe (``HOROVOD_SPARSE_DEDUPE``, the default) the exchange
    runs over the batch's UNIQUE ids; ``inv`` is the inverse index
    scattering unique rows back to input order, and the backward
    accumulates duplicate-id gradients through it before routing.
    ``inv is None`` means the exchange carried the raw batch.
    """

    __slots__ = ("perm", "send_counts", "recv_splits", "recv_slots",
                 "n_ids", "inv", "n_unique")

    def __init__(self, perm, send_counts, recv_splits, recv_slots,
                 n_ids, inv=None, n_unique=None):
        self.perm = perm
        self.send_counts = send_counts
        self.recv_splits = recv_splits
        self.recv_slots = recv_slots
        self.n_ids = n_ids
        self.inv = inv
        self.n_unique = n_unique if n_unique is not None else n_ids


class _PendingLookup:
    """In-flight state of one table's staged lookup (the overlapped
    multi-table path drives several of these concurrently)."""

    __slots__ = ("table", "t0", "ids", "ex_ids", "inv", "call",
                 "perm", "send_ids", "send_counts", "handle",
                 "recv_splits", "recv_slots", "out")

    def __init__(self, table, t0, ids, ex_ids, inv, call):
        self.table = table
        self.t0 = t0
        self.ids = ids
        self.ex_ids = ex_ids
        self.inv = inv
        self.call = call
        self.perm = None
        self.send_ids = None
        self.send_counts = None
        self.handle = None
        self.recv_splits = None
        self.recv_slots = None
        self.out = None


class ShardedEmbedding:
    """One embedding table, row-sharded across the Horovod world.

    ``rank``/``size`` default to the live Horovod world; pass them
    explicitly (with ``size=1``) to use the engine without ``hvd.init``
    (unit tests, single-process trainers — lookups are then purely
    local).  Row init is deterministic per (name, seed, row): every
    world size materializes bit-identical tables, so elastic resizes
    only need the checkpoint for *trained* state.
    """

    def __init__(self, name: str, num_rows: int, dim: int,
                 rank: Optional[int] = None,
                 size: Optional[int] = None,
                 seed: int = 0, dtype=np.float32,
                 init_scale: float = 0.01):
        if num_rows <= 0 or dim <= 0:
            raise ValueError("num_rows and dim must be positive")
        if (rank is None) != (size is None):
            raise ValueError("pass both rank and size or neither")
        if rank is None:
            rank, size = _hvd_rank_size()
        if not 0 <= rank < size:
            raise ValueError("rank %d outside world of %d"
                             % (rank, size))
        self.name = str(name)
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.rank = int(rank)
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.seed = int(seed)
        # Round-robin ownership: global row id -> (id % size) owner,
        # (id // size) local slot.
        self._local_ids = np.arange(self.rank, self.num_rows,
                                    self.size, dtype=np.int64)
        self.local = self._init_rows(self._local_ids,
                                     float(init_scale))
        # Touch tracking is GENERATIONAL, not a boolean mask: each
        # apply stamps its slots with a fresh generation, and a
        # subset clear removes only slots not re-touched since the
        # snapshot it came from — a row updated while its delta save
        # was in flight stays marked for the next delta (a plain
        # mask cannot tell pre- from post-snapshot touches and would
        # silently drop such rows from the chain).
        self._touch_gen = np.zeros(len(self._local_ids), np.int64)
        self._gen = 0
        self._snap_gen = 0
        self._ctx: Optional[_LookupContext] = None
        self._lock = threading.Lock()
        self._call = 0

    # ------------------------------------------------------------------
    # init / addressing
    # ------------------------------------------------------------------
    def _init_rows(self, ids: np.ndarray, scale: float) -> np.ndarray:
        """Deterministic, SEEKABLE per-(row, col) init: a splitmix64
        hash of (seed, table, row*dim+col) mapped to uniform
        [-scale, scale).  Counter-based, so a rank materializes ONLY
        the rows it was asked for — O(len(ids)·dim), never
        O(num_rows) — and every world size computes bit-identical
        values for the same global row (sequential generators can't
        seek, and generating the full table per rank to slice 1/size
        of it defeats row-sharding at recsys scale)."""
        table_seed = np.uint64(int.from_bytes(
            self.name.encode()[:8].ljust(8, b"\0"), "little"))
        ctr = (ids[:, None].astype(np.uint64)
               * np.uint64(self.dim)
               + np.arange(self.dim, dtype=np.uint64)[None, :])
        with np.errstate(over="ignore"):
            z = (ctr + np.uint64(self.seed)
                 * np.uint64(0x9E3779B97F4A7C15) + table_seed)
            z = (z + np.uint64(0x9E3779B97F4A7C15))
            z = (z ^ (z >> np.uint64(30))) \
                * np.uint64(0xBF58476D1CE4E5B9)
            z = (z ^ (z >> np.uint64(27))) \
                * np.uint64(0x94D049BB133111EB)
            z = z ^ (z >> np.uint64(31))
        u = (z >> np.uint64(11)).astype(np.float64) / float(2 ** 53)
        return ((2.0 * u - 1.0) * scale).astype(self.dtype)

    def owner_of(self, ids: np.ndarray) -> np.ndarray:
        return (np.asarray(ids, np.int64) % self.size)

    def slot_of(self, ids: np.ndarray) -> np.ndarray:
        return (np.asarray(ids, np.int64) // self.size)

    @property
    def local_ids(self) -> np.ndarray:
        return self._local_ids

    # ------------------------------------------------------------------
    # forward / backward
    # ------------------------------------------------------------------
    def _check_ids(self, ids: np.ndarray):
        if ids.ndim != 1:
            raise ValueError("lookup ids must be 1-D, got shape %s"
                             % (ids.shape,))
        if len(ids) and (ids.min() < 0 or ids.max() >= self.num_rows):
            raise ValueError(
                "lookup ids out of range [0, %d): min %d max %d"
                % (self.num_rows, ids.min(), ids.max()))

    def lookup(self, ids) -> np.ndarray:
        """Gather rows for ``ids`` (any rank's rows) via the alltoall
        exchange; returns ``(len(ids), dim)`` in input order.  EVERY
        rank must call lookup for the same table in the same step
        (splits may differ — that is the point), like any collective.

        With ``HOROVOD_SPARSE_DEDUPE`` (default on) only the batch's
        UNIQUE ids cross the wire — on Zipf-shaped traffic repeated
        hot ids dominate, so the ids/rows/grads payloads all shrink —
        and rows scatter back through the inverse index.  The staged
        helpers below are shared with :func:`lookup_overlapped`, which
        keeps several tables' exchanges in flight together.
        """
        p = self._lookup_start(ids)
        if self.size == 1:
            return self._lookup_finish_local(p)
        self._lookup_route(p)
        recv_ids, recv_splits = _alltoall(
            p.send_ids, p.send_counts,
            name="sparse.%s.ids.%d" % (self.name, p.call))
        served = self._lookup_serve(p, recv_ids, recv_splits)
        rows, _ = _alltoall(
            served, p.recv_splits,
            name="sparse.%s.rows.%d" % (self.name, p.call))
        return self._lookup_finish(p, rows)

    # --- staged lookup internals (shared by lookup_overlapped) --------
    def _lookup_start(self, ids) -> _PendingLookup:
        """Local prep: validate, dedupe (when enabled), claim a call
        number."""
        t0 = time.perf_counter()
        ids = np.ascontiguousarray(np.asarray(ids, np.int64))
        self._check_ids(ids)
        if _env.sparse_dedupe_enabled():
            ex_ids, inv = np.unique(ids, return_inverse=True)
            ex_ids = np.ascontiguousarray(ex_ids)
        else:
            ex_ids, inv = ids, None
        return _PendingLookup(self, t0, ids, ex_ids, inv,
                              self._next_call())

    def _lookup_finish_local(self, p: "_PendingLookup") -> np.ndarray:
        slots = self.slot_of(p.ex_ids)
        self._ctx = _LookupContext(None, None, None, slots,
                                   len(p.ids), inv=p.inv,
                                   n_unique=len(p.ex_ids))
        gathered = self.local[slots]         # fancy index: a copy
        out = gathered if p.inv is None else gathered[p.inv]
        _LOOKUP_SECONDS.observe(
            time.perf_counter() - p.t0, op="lookup")
        return out

    def _lookup_route(self, p: "_PendingLookup"):
        """Compute the owner-sorted send layout for the ids
        exchange."""
        owners = self.owner_of(p.ex_ids)
        p.perm = np.argsort(owners, kind="stable")
        p.send_ids = np.ascontiguousarray(p.ex_ids[p.perm])
        p.send_counts = np.bincount(owners, minlength=self.size
                                    ).astype(np.int64)

    def _lookup_serve(self, p: "_PendingLookup", recv_ids,
                      recv_splits) -> np.ndarray:
        """Serve the locally owned rows requested by peers (between
        the ids and rows exchanges)."""
        _A2A_OPS.inc(1, stage="ids")
        _A2A_BYTES.inc(int(p.send_ids.nbytes), stage="ids")
        p.recv_splits = np.asarray(recv_splits, np.int64)
        p.recv_slots = self.slot_of(np.asarray(recv_ids))
        served = np.ascontiguousarray(self.local[p.recv_slots])
        _A2A_OPS.inc(1, stage="rows")
        _A2A_BYTES.inc(int(served.nbytes), stage="rows")
        return served

    def _lookup_finish(self, p: "_PendingLookup",
                       rows) -> np.ndarray:
        """Scatter exchanged rows back to input order and park the
        routing context for the backward."""
        gathered = np.empty((len(p.ex_ids), self.dim), self.dtype)
        gathered[p.perm] = rows
        out = gathered if p.inv is None else gathered[p.inv]
        self._ctx = _LookupContext(p.perm, p.send_counts,
                                   p.recv_splits, p.recv_slots,
                                   len(p.ids), inv=p.inv,
                                   n_unique=len(p.ex_ids))
        _LOOKUP_SECONDS.observe(
            time.perf_counter() - p.t0, op="lookup")
        return out

    def apply_gradients(self, grad, lr: float = 0.01):
        """Route ``grad`` — ``(len(ids), dim)`` w.r.t. the last
        lookup's output — back to the owning ranks and apply a sparse
        SGD update (``row -= lr * grad``; duplicate ids accumulate).
        Marks every updated row touched."""
        t0 = time.perf_counter()
        ctx, self._ctx = self._ctx, None
        if ctx is None:
            raise RuntimeError(
                "apply_gradients without a preceding lookup on table "
                "%r" % self.name)
        grad = np.ascontiguousarray(np.asarray(grad, self.dtype))
        if grad.shape != (ctx.n_ids, self.dim):
            raise ValueError(
                "grad shape %s does not match last lookup (%d, %d)"
                % (grad.shape, ctx.n_ids, self.dim))
        if ctx.inv is not None:
            # Deduped lookup: duplicate-id gradients accumulate into
            # one row per unique id BEFORE the lr scaling and the
            # exchange, in table dtype — so the wire carries (and the
            # owner applies) one update per unique id per requester.
            acc = np.zeros((ctx.n_unique, self.dim), self.dtype)
            np.add.at(acc, ctx.inv, grad)
            grad = acc
        if self.size == 1:
            grad_recv, recv_slots = grad, ctx.recv_slots
        else:
            call = self._next_call()
            grad_recv, _ = _alltoall(
                grad[ctx.perm], ctx.send_counts,
                name="sparse.%s.grads.%d" % (self.name, call))
            _A2A_OPS.inc(1, stage="grads")
            _A2A_BYTES.inc(int(grad.nbytes), stage="grads")
            recv_slots = ctx.recv_slots
        # Update stays in table dtype end to end: a float64 detour
        # would round differently from the plain `table -= lr*g` a
        # single-process trainer runs, breaking bit-identity checks.
        upd = (lr * grad_recv).astype(self.dtype, copy=False)
        np.subtract.at(self.local, recv_slots, upd)
        self._gen += 1
        self._touch_gen[recv_slots] = self._gen
        _LOOKUP_SECONDS.observe(
            time.perf_counter() - t0, op="apply_gradients")

    def _next_call(self) -> int:
        with self._lock:
            self._call += 1
            return self._call

    # ------------------------------------------------------------------
    # touched-row lifecycle (differential checkpoints)
    # ------------------------------------------------------------------
    def touched_count(self) -> int:
        return int((self._touch_gen > 0).sum())

    def snapshot_touched(self) -> np.ndarray:
        """LOCAL slot indices touched since the last clear (sorted).
        Also records the current touch generation: a later
        ``clear_touched(slots)`` forgets only touches up to THIS
        point, so updates that land while the save is in flight stay
        marked."""
        self._snap_gen = self._gen
        return np.flatnonzero(self._touch_gen > 0)

    def clear_touched(self, slots: Optional[np.ndarray] = None):
        """Forget touched marks — call ONLY after the delta carrying
        them is durably committed.  With ``slots`` (the most recent
        ``snapshot_touched`` result), rows re-touched after that
        snapshot stay marked for the next delta; without, everything
        clears (use after a FULL base only)."""
        if slots is None:
            self._touch_gen[:] = 0
            self._gen = 0
            self._snap_gen = 0
        else:
            slots = np.asarray(slots, np.int64)
            stale = slots[self._touch_gen[slots] <= self._snap_gen]
            self._touch_gen[stale] = 0

    # ------------------------------------------------------------------
    # durable state (RowDelta items over the checkpoint pipeline)
    # ------------------------------------------------------------------
    def item_prefix(self) -> str:
        return "sparse/%s/rows" % self.name

    def item_name(self) -> str:
        """This rank's checkpoint item name (its shard of the
        table)."""
        return "%s.r%05d" % (self.item_prefix(), self.rank)

    def durable_items(self, full: bool) -> Dict[str, RowDelta]:
        """This rank's checkpoint item: all owned rows (``full=True``,
        a base) or only the touched ones (a delta).  Values are
        copies — safe to hand to the async writer."""
        if full:
            ids, values = self._local_ids, self.local.copy()
        else:
            slots = self.snapshot_touched()
            ids = self._local_ids[slots]
            values = self.local[slots].copy()
        return {self.item_name():
                RowDelta(ids, values, self.num_rows)}

    def load_durable_items(self, items: Dict[str, object]):
        """Rebuild the local slice from restored checkpoint items —
        written at ANY world size (N→M→N resize: the full table is
        assembled from every historical shard's RowDelta, then
        re-sliced by the current ownership map)."""
        table = assemble_table(items, self.item_prefix(),
                               dtype=self.dtype)
        if table is None:
            raise KeyError(
                "no checkpoint items under %r" % self.item_prefix())
        if table.shape != (self.num_rows, self.dim):
            raise ValueError(
                "restored table %r has shape %s, expected (%d, %d)"
                % (self.name, table.shape, self.num_rows, self.dim))
        self.local = np.ascontiguousarray(
            table[self._local_ids]).astype(self.dtype)
        self._touch_gen = np.zeros(len(self._local_ids), np.int64)
        self._gen = 0
        self._snap_gen = 0
        self._ctx = None

    def full_table(self, items: Optional[Dict[str, object]] = None
                   ) -> np.ndarray:
        """The complete table.  With ``items`` (a restored checkpoint
        dict) it is assembled from shards; without, from the LIVE
        local slices via an allgather-free alltoall-less path — only
        valid at size 1 (tests); multi-rank callers should restore."""
        if items is not None:
            return assemble_table(items, self.item_prefix(),
                                  dtype=self.dtype)
        if self.size != 1:
            raise RuntimeError(
                "full_table() without items is single-rank only")
        return self.local.copy()


def lookup_overlapped(tables: Sequence[ShardedEmbedding],
                      ids_list: Sequence) -> List[np.ndarray]:
    """Look up several tables with their alltoall exchanges in flight
    TOGETHER: all ids exchanges are issued async first, each table's
    rows are served and its rows exchange issued as its ids land, and
    everything is gathered at the end — so table k's wire time hides
    behind table j's serve/scatter work instead of serializing after
    it (a DLRM step touches dozens of tables back to back).

    Per table the staged math is byte-for-byte the code ``lookup``
    runs (same helpers, same op order within a table), so results are
    bit-identical to the serial path, and each table's backward
    context is parked exactly as a plain lookup would — call
    ``apply_gradients`` per table afterwards as usual.  Tables must be
    distinct; every rank must call this with the same table list.
    """
    if len(tables) != len(ids_list):
        raise ValueError("need one ids batch per table (%d vs %d)"
                         % (len(tables), len(ids_list)))
    if len(set(id(t) for t in tables)) != len(tables):
        raise ValueError("tables must be distinct")
    from ..ops import eager
    pend = [t._lookup_start(ids)
            for t, ids in zip(tables, ids_list)]
    outs: List[Optional[np.ndarray]] = [None] * len(pend)
    remote = []
    for i, p in enumerate(pend):
        if p.table.size == 1:
            outs[i] = p.table._lookup_finish_local(p)
        else:
            p.table._lookup_route(p)
            p.handle = eager.alltoall_async(
                p.send_ids, splits=p.send_counts,
                name="sparse.%s.ids.%d" % (p.table.name, p.call))
            remote.append(i)
    for i in remote:
        p = pend[i]
        recv_ids, recv_splits = eager.synchronize(p.handle)
        served = p.table._lookup_serve(p, np.asarray(recv_ids),
                                       np.asarray(recv_splits))
        p.handle = eager.alltoall_async(
            served, splits=p.recv_splits,
            name="sparse.%s.rows.%d" % (p.table.name, p.call))
    for i in remote:
        p = pend[i]
        rows, _ = eager.synchronize(p.handle)
        outs[i] = p.table._lookup_finish(p, np.asarray(rows))
    return outs


class EmbeddingBag:
    """Sum/mean-pool looked-up rows per example (the DLRM bag shape).

    ``offsets`` follow the torch EmbeddingBag convention: example i
    owns ids[offsets[i]:offsets[i+1]].  The backward expands a bag
    gradient back to per-id row gradients (mean divides by bag size).
    """

    def __init__(self, table: ShardedEmbedding, mode: str = "sum"):
        if mode not in ("sum", "mean"):
            raise ValueError("mode must be 'sum' or 'mean'")
        self.table = table
        self.mode = mode
        self._sizes: Optional[np.ndarray] = None

    def forward(self, ids, offsets) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        offsets = np.asarray(offsets, np.int64)
        rows = self.table.lookup(ids)
        sizes = np.diff(np.concatenate([offsets, [len(ids)]]))
        if (sizes < 0).any():
            raise ValueError("offsets must be non-decreasing")
        self._sizes = sizes
        seg = np.repeat(np.arange(len(offsets)), sizes)
        out = np.zeros((len(offsets), self.table.dim),
                       self.table.dtype)
        np.add.at(out, seg, rows)
        if self.mode == "mean":
            out /= np.maximum(sizes, 1)[:, None]
        return out

    def backward(self, bag_grad, lr: float = 0.01):
        """Expand the per-bag gradient to per-id gradients and apply
        them through the table's alltoall backward."""
        if self._sizes is None:
            raise RuntimeError("backward before forward")
        sizes, self._sizes = self._sizes, None
        bag_grad = np.asarray(bag_grad, self.table.dtype)
        if self.mode == "mean":
            bag_grad = bag_grad / np.maximum(sizes, 1)[:, None]
        row_grad = np.repeat(bag_grad, sizes, axis=0)
        self.table.apply_gradients(row_grad, lr=lr)
