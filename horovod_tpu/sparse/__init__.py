"""Model-parallel sparse embedding engine — the recsys/DLRM workload.

Dense data-parallel training allreduces every gradient; DLRM-style
recommenders instead keep their dominant state — embedding tables with
millions of rows — **model-parallel**: each rank owns a slice of every
table, a training step looks up only the rows its batch touches, and
the lookup/gradient exchange is an **alltoall**, not an allreduce
(Check-N-Run, NSDI '22; see PAPERS.md).  This package opens that
traffic pattern on the existing eager plane:

* :class:`~.embedding.ShardedEmbedding` splits tables row-wise across
  ranks (round-robin by row id, so hot rows spread evenly), exchanges
  per-rank index batches and gathered rows through the
  splits-piggybacking ``hvd.alltoall`` (the coordinator hands every
  rank its recv splits in the negotiation response — no data-plane
  split exchange), and applies sparse gradient updates locally.
* Every update records its rows in a **touched-row set** per table
  since the last committed checkpoint, which is exactly what the
  differential checkpoint layer persists
  (:class:`horovod_tpu.checkpoint.RowDelta`): a periodic full base
  plus touched-rows-only deltas, cutting checkpoint bytes to the
  touch rate.
* :class:`~.embedding.EmbeddingBag` pools looked-up rows per example
  (sum/mean), the DLRM interaction-input shape.

The per-step split vectors legally vary with the batch, so cycles
containing these alltoalls are exactly the traffic steady-state
replay must never freeze — ``hvd_steady_state_exits{reason=alltoall}``
labels both the submit-side and delivery-side exits.

See docs/sparse_embedding.md for the exchange protocol and
models/dlrm.py + bench.py (``--only dlrm``) for the workload.
"""

from .embedding import (EmbeddingBag, ShardedEmbedding,
                        lookup_overlapped)

__all__ = ["ShardedEmbedding", "EmbeddingBag", "lookup_overlapped"]
