"""BERT encoder family in Flax, bfloat16-first.

BERT-large pretraining is a reference headline workload (Adasum BERT
target in BASELINE.md; reference: docs/adasum_user_guide.rst,
examples/adasum/).  The reference has no model zoo of its own (it wraps
user models); this module provides the flagship model the framework's
benchmarks, Adasum runs and sharded-training paths exercise.

TPU-first design: all matmuls in bfloat16 (fp32 params), static shapes,
attention as batched einsums that tile onto the MXU, and parameter
naming chosen so :func:`horovod_tpu.parallel.sharding.bert_partition_rules`
can map kernels onto tensor-parallel mesh axes.
"""

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    # Use jax.checkpoint on each layer to trade FLOPs for HBM
    # (rematerialisation; essential for long sequence / large batch).
    remat: bool = False
    # "einsum": plain XLA attention (supports padding masks, lets GSPMD
    # shard freely).  "flash": the Pallas flash kernel
    # (ops/pallas_attention.py) — O(S) memory, fused online softmax;
    # padding masks are not yet supported by the kernel.
    attention_impl: str = "einsum"


def bert_large_config(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_base_config(**kw) -> BertConfig:
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072, **kw)


def bert_tiny_config(**kw) -> BertConfig:
    """Tiny config for tests and multi-chip dry runs."""
    defaults = dict(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128,
                    max_position_embeddings=128,
                    hidden_dropout=0.0, attention_dropout=0.0)
    defaults.update(kw)
    return BertConfig(**defaults)


class SelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(
            features=(cfg.num_heads, head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        if cfg.attention_impl == "flash":
            if mask is not None:
                raise NotImplementedError(
                    "attention_impl='flash' does not support padding "
                    "masks yet; use 'einsum' or drop the mask.")
            if cfg.attention_dropout > 0.0 and not deterministic:
                raise NotImplementedError(
                    "attention_impl='flash' does not apply attention "
                    "dropout; set attention_dropout=0 or use 'einsum'.")
            from ..ops.pallas_attention import flash_attention
            ctx = flash_attention(q, k, v).astype(cfg.dtype)
        else:
            # [batch, heads, q_len, k_len] — contraction and the
            # subsequent PV matmul are the MXU hot loops.
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            scores = scores / math.sqrt(head_dim)
            if mask is not None:
                big_neg = jnp.finfo(cfg.dtype).min
                scores = jnp.where(mask[:, None, None, :], scores,
                                   big_neg)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            probs = probs.astype(cfg.dtype)
            probs = nn.Dropout(cfg.attention_dropout)(
                probs, deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="out")(ctx)
        return out


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool = True):
        cfg = self.config
        attn_out = SelfAttention(cfg, name="attention")(
            x, mask, deterministic)
        attn_out = nn.Dropout(cfg.hidden_dropout)(
            attn_out, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32,
                         name="attention_norm")(x + attn_out)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="intermediate")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="output")(h)
        h = nn.Dropout(cfg.hidden_dropout)(h, deterministic=deterministic)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            param_dtype=jnp.float32,
                            name="output_norm")(x + h)


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config
        b, s = input_ids.shape
        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="word_embeddings")(input_ids)
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="position_embeddings")(
            jnp.arange(s)[None, :])
        emb = emb + pos
        if cfg.type_vocab_size:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            emb = emb + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                                 dtype=cfg.dtype, param_dtype=jnp.float32,
                                 name="token_type_embeddings")(
                token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32,
                         name="embeddings_norm")(emb)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)

        layer_cls = BertLayer
        if cfg.remat:
            layer_cls = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(
                x, attention_mask, deterministic)
        return x


class BertForMaskedLM(nn.Module):
    """Encoder + tied-embedding MLM head (the pretraining objective used
    by the Adasum BERT-large baseline)."""
    config: BertConfig

    def setup(self):
        cfg = self.config
        self.encoder = BertEncoder(cfg)
        self.mlm_transform = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                      param_dtype=jnp.float32)
        self.mlm_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                     dtype=cfg.dtype,
                                     param_dtype=jnp.float32)
        self.mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                                   (cfg.vocab_size,), jnp.float32)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config
        x = self.encoder(input_ids, token_type_ids, attention_mask,
                         deterministic)
        x = self.mlm_transform(x)
        x = nn.gelu(x, approximate=True)
        x = self.mlm_norm(x)
        # Tied output projection: reuse the word embedding matrix.
        embedding = self.encoder.variables[
            "params"]["word_embeddings"]["embedding"]
        logits = jnp.einsum("bsh,vh->bsv", x, embedding.astype(cfg.dtype))
        return logits.astype(jnp.float32) + self.mlm_bias


def mlm_loss(logits, labels, mask):
    """Cross-entropy over masked positions; ``mask`` is 1 where the token
    was masked (predicted)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
