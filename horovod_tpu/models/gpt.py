"""GPT-style decoder-only language model family in Flax, bfloat16-first.

Completes the model zoo's transformer coverage next to the BERT
encoder family (the reference wraps user models and ships none of its
own; this zoo is what the framework's benchmarks, Adasum runs and
sharded-training paths exercise — SURVEY §2 model-family rows).

TPU-first design mirrors bert.py: all matmuls in bfloat16 (fp32
params), static shapes, attention as batched einsums that tile onto
the MXU (or the Pallas flash kernel with ``causal=True`` for O(S)
memory), pre-LayerNorm residual blocks, optional per-layer
``jax.checkpoint`` rematerialisation, and parameter naming matched by
:func:`horovod_tpu.parallel.sharding.gpt_partition_rules` so kernels
map onto tensor-parallel mesh axes.
"""

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # "einsum": plain XLA attention; "flash": the Pallas kernel
    # (ops/pallas_attention.py, causal=True).
    attention_impl: str = "einsum"


def gpt2_small_config(**kw) -> GPTConfig:
    return GPTConfig(**kw)


def gpt2_medium_config(**kw) -> GPTConfig:
    defaults = dict(hidden_size=1024, num_layers=24, num_heads=16,
                    intermediate_size=4096)
    defaults.update(kw)
    return GPTConfig(**defaults)


def gpt_tiny_config(**kw) -> GPTConfig:
    """Tiny config for tests and multi-chip dry runs."""
    defaults = dict(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128,
                    max_position_embeddings=128, dropout=0.0)
    defaults.update(kw)
    return GPTConfig(**defaults)


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(
            features=(cfg.num_heads, head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        if cfg.attention_impl == "flash":
            if cfg.dropout > 0.0 and not deterministic:
                raise NotImplementedError(
                    "attention_impl='flash' does not apply attention "
                    "dropout; set dropout=0 or use 'einsum' (same "
                    "guard as the BERT family).")
            from ..ops.pallas_attention import flash_attention
            ctx = flash_attention(q, k, v, causal=True).astype(cfg.dtype)
        else:
            seq = x.shape[1]
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            scores = scores / math.sqrt(head_dim)
            causal = jnp.tril(jnp.ones((seq, seq), bool))
            scores = jnp.where(causal[None, None],
                               scores, jnp.finfo(cfg.dtype).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            probs = probs.astype(cfg.dtype)
            probs = nn.Dropout(cfg.dropout)(probs,
                                            deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, param_dtype=jnp.float32,
                               name="out")(ctx)


class GPTBlock(nn.Module):
    """Pre-LN residual block (GPT-2 layout)."""
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        norm = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        h = CausalSelfAttention(cfg, name="attention")(
            norm("attention_norm")(x), deterministic)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        x = x + h
        m = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="intermediate")(
            norm("mlp_norm")(x))
        m = nn.gelu(m, approximate=True)
        m = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="output")(m)
        m = nn.Dropout(cfg.dropout)(m, deterministic=deterministic)
        return x + m


class GPTLMHeadModel(nn.Module):
    """Decoder stack + tied-embedding LM head."""
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        seq = input_ids.shape[1]
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="word_embeddings")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="position_embeddings")
        x = wte(input_ids) + wpe(jnp.arange(seq)[None, :])
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        block = GPTBlock
        if cfg.remat:
            block = nn.remat(GPTBlock, static_argnums=(2,))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="final_norm")(x)
        logits = jnp.einsum("bsh,vh->bsv", x,
                            wte.embedding.astype(cfg.dtype))
        return logits.astype(jnp.float32)


def lm_loss(logits, input_ids, mask=None):
    """Next-token cross-entropy: position t predicts token t+1.
    ``mask`` (optional) is 1 where the TARGET token counts."""
    logits = logits[:, :-1]
    targets = input_ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return -ll.mean()
    m = mask[:, 1:].astype(jnp.float32)
    return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)
