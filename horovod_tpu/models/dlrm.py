"""DLRM-tiny: the dense half of a recsys click-through model.

The canonical DLRM shape (Naumov et al.; productionized per
Check-N-Run, NSDI '22): a bottom MLP embeds dense features, sparse
categorical features hit embedding tables (model-parallel, served by
``horovod_tpu/sparse/``), and a top MLP scores the concatenation of
the dense vector with the pooled embedding vectors.  This module is
deliberately framework-split: the flax part here is everything that
allreduces (data-parallel dense params); the embedding tables stay
OUTSIDE jit in the sparse engine because their exchange is an eager
alltoall with per-step-varying splits.

The interaction is plain concatenation (dot-interaction adds nothing
to the systems story being benched); ``dlrm_tiny_config`` keeps
shapes small enough for 8 CPU worker processes while the tables stay
big enough that a delta checkpoint is ~1-2 orders of magnitude
smaller than a full one at the synthetic touch rate.
"""

from dataclasses import dataclass
from typing import List, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclass
class DLRMConfig:
    num_dense: int = 4                 # dense feature count
    embed_dim: int = 16                # rows are (embed_dim,)
    table_rows: Tuple[int, ...] = (65536, 65536)
    ids_per_table: int = 2             # multi-hot width per example
    bottom: Tuple[int, ...] = (32, 16)  # bottom MLP widths
    top: Tuple[int, ...] = (32, 16)     # top MLP widths (then 1)

    @property
    def num_tables(self) -> int:
        return len(self.table_rows)


def dlrm_tiny_config() -> DLRMConfig:
    return DLRMConfig()


class DLRMDense(nn.Module):
    """Bottom MLP + top MLP over [dense_vec, per-table pooled
    embeddings]; returns raw logits ``(batch,)``."""
    config: DLRMConfig

    @nn.compact
    def __call__(self, dense, emb):
        cfg = self.config
        x = dense
        for w in cfg.bottom:
            x = nn.relu(nn.Dense(w)(x))
        # emb: (batch, num_tables * embed_dim) — pooled by the sparse
        # engine's EmbeddingBag, already in example order.
        z = jnp.concatenate([x, emb], axis=-1)
        for w in cfg.top:
            z = nn.relu(nn.Dense(w)(z))
        return nn.Dense(1)(z)[..., 0]


def bce_logits_loss(logits, labels):
    """Numerically stable sigmoid binary cross-entropy."""
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    return jnp.mean(jnp.clip(logits, 0) - logits * labels +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))


def synthetic_click_batch(rng: np.random.Generator, batch: int,
                          config: DLRMConfig
                          ) -> Tuple[np.ndarray, List[np.ndarray],
                                     np.ndarray, np.ndarray]:
    """One synthetic batch: ``(dense, ids_per_table, offsets,
    labels)``.  Ids are Zipf-skewed (hot-row heavy, the production
    access pattern differential checkpoints exploit) and clipped to
    the table; offsets are the fixed-width bag boundaries."""
    dense = rng.standard_normal((batch, config.num_dense)
                                ).astype(np.float32)
    ids = []
    for rows in config.table_rows:
        raw = rng.zipf(1.3, size=batch * config.ids_per_table)
        ids.append(((raw - 1) % rows).astype(np.int64))
    offsets = (np.arange(batch, dtype=np.int64)
               * config.ids_per_table)
    labels = (rng.random(batch) < 0.3).astype(np.float32)
    return dense, ids, offsets, labels
