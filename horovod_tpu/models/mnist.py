"""Small MNIST models — the keras_mnist baseline workload
(reference: examples/keras/keras_mnist.py uses a small convnet with
DistributedOptimizer; BASELINE.md lists it as the CPU/Gloo config)."""

import flax.linen as nn
import jax.numpy as jnp


class MnistMLP(nn.Module):
    hidden: int = 512
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class MnistCNN(nn.Module):
    """Matches the topology of the reference example's Keras model
    (examples/keras/keras_mnist.py: conv 32 → conv 64 → pool → dense)."""
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def cross_entropy_loss(logits, labels):
    logp = jnp.take_along_axis(
        nn.log_softmax(logits), labels[:, None], axis=-1)
    return -logp.mean()
