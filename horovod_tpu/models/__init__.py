from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .bert import (BertConfig, BertEncoder, BertForMaskedLM,
                   bert_base_config, bert_large_config, bert_tiny_config,
                   mlm_loss)
from .mnist import MnistCNN, MnistMLP, cross_entropy_loss

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
    "ResNet152",
    "BertConfig", "BertEncoder", "BertForMaskedLM", "bert_base_config",
    "bert_large_config", "bert_tiny_config", "mlm_loss",
    "MnistCNN", "MnistMLP", "cross_entropy_loss",
]
