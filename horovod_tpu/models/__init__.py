from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .bert import (BertConfig, BertEncoder, BertForMaskedLM,
                   bert_base_config, bert_large_config, bert_tiny_config,
                   mlm_loss)
from .gpt import (GPTConfig, GPTLMHeadModel, gpt2_medium_config,
                  gpt2_small_config, gpt_tiny_config, lm_loss)
from .mnist import MnistCNN, MnistMLP, cross_entropy_loss
from .dlrm import (DLRMConfig, DLRMDense, bce_logits_loss,
                   dlrm_tiny_config, synthetic_click_batch)

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
    "ResNet152",
    "BertConfig", "BertEncoder", "BertForMaskedLM", "bert_base_config",
    "bert_large_config", "bert_tiny_config", "mlm_loss",
    "GPTConfig", "GPTLMHeadModel", "gpt2_small_config",
    "gpt2_medium_config", "gpt_tiny_config", "lm_loss",
    "MnistCNN", "MnistMLP", "cross_entropy_loss",
    "DLRMConfig", "DLRMDense", "bce_logits_loss", "dlrm_tiny_config",
    "synthetic_click_batch",
]
