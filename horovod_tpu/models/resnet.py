"""ResNet v1.5 family in Flax, bfloat16-first for the TPU MXU.

The benchmark workhorse: the reference's headline numbers are ResNet
synthetic-benchmark images/sec (reference:
examples/tensorflow2/tensorflow2_synthetic_benchmark.py,
examples/pytorch/pytorch_synthetic_benchmark.py — metric defined at
pytorch_synthetic_benchmark.py:106-118; docs/benchmarks.rst:32-43).

Design notes (TPU-first):
  * compute in bfloat16, parameters and batch-norm statistics in float32
    (the MXU natively consumes bf16; fp32 accumulation is automatic);
  * NHWC layout (XLA's preferred conv layout on TPU);
  * no data-dependent control flow — fully static graph for one-time
    compilation.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride on the 3x3, not the 1x1 (matches torchvision /
        # tf_cnn_benchmarks used by the reference benchmarks).
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   conv=conv, norm=norm, act=self.act,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2],
                   block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckResNetBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckResNetBlock)
