"""Sharded SPMD training steps for the flagship models.

The TPU-native core training path: one jit-compiled step per model whose
parameters, optimizer state and activations are laid out over a named
mesh (dp / tp / sp / fsdp axes), with XLA inserting the gradient
allreduce and tensor-parallel collectives (GSPMD).  This is what
replaces the reference's DistributedOptimizer+NCCL pipeline at full
performance (reference: torch/optimizer.py:110-236,
tensorflow/__init__.py:334-381 — gradient hooks feeding allreduce); the
drop-in per-gradient API also exists (horovod_tpu.jax) but this is the
path that hits peak MXU/ICI utilisation.
"""

from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.bert import BertConfig, BertForMaskedLM, mlm_loss
from .parallel.sharding import (bert_partition_rules, infer_shardings,
                                Rules)


class TrainState(train_state.TrainState):
    pass


def factor_mesh_axes(n_devices: int) -> Dict[str, int]:
    """Factor a device count into (dp, tp, sp) sizes, preferring dp.

    8 → dp2·tp2·sp2, 4 → dp2·tp2, 2 → dp2, 1 → all-1 (degenerate).
    """
    axes = {"dp": 1, "tp": 1, "sp": 1}
    rest = n_devices
    for name in ("dp", "tp", "sp"):
        if rest % 2 == 0:
            axes[name] = 2
            rest //= 2
    axes["dp"] *= rest  # absorb any remainder into dp
    return axes


def make_bert_pretrain_step(
        config: BertConfig, mesh: Mesh,
        learning_rate: float = 1e-4,
        rules: Optional[Rules] = None,
        donate: bool = True,
        dropout_seed: int = 0,
) -> Tuple[Callable, "NamedSharding"]:
    """Returns ``(make_jitted, batch_sharding)``.

    ``make_jitted(example_batch)`` builds and returns the jit-compiled
    ``(init_fn, step_fn)`` pair for that batch's shapes (shapes are
    needed to lay out the state sharding before compilation);
    ``batch_sharding`` is the NamedSharding inputs must be placed with.

    * params/opt-state sharded by Megatron-style rules (tp [+ fsdp]);
    * batch sharded (dp, sp) over (batch, sequence);
    * dropout active whenever the config's dropout rates are non-zero,
      with the rng folded from the step counter (deterministic replay);
    * gradient reduction over dp and the tp/sp collectives are inserted
      by XLA (GSPMD) — on TPU hardware they ride ICI.
    """
    model = BertForMaskedLM(config)
    tx = optax.adamw(learning_rate, weight_decay=0.01)
    rules = rules or bert_partition_rules(
        tp="tp" if "tp" in mesh.shape else None,
        fsdp="fsdp" if "fsdp" in mesh.shape else None)
    deterministic = (config.hidden_dropout == 0.0
                     and config.attention_dropout == 0.0)

    batch_spec = P("dp" if "dp" in mesh.shape else None,
                   "sp" if "sp" in mesh.shape else None)
    batch_sharding = NamedSharding(mesh, batch_spec)
    repl = NamedSharding(mesh, P())

    def _init(rng, batch):
        params = model.init(rng, batch["input_ids"],
                            deterministic=True)["params"]
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=tx)

    def _loss_fn(params, batch, dropout_rng):
        rngs = None if deterministic else {"dropout": dropout_rng}
        logits = model.apply({"params": params}, batch["input_ids"],
                             attention_mask=batch.get("attention_mask"),
                             deterministic=deterministic, rngs=rngs)
        return mlm_loss(logits, batch["labels"], batch["mask"])

    def _step(state, batch):
        dropout_rng = jax.random.fold_in(
            jax.random.PRNGKey(dropout_seed), state.step)
        loss, grads = jax.value_and_grad(_loss_fn)(
            state.params, batch, dropout_rng)
        new_state = state.apply_gradients(grads=grads)
        return new_state, loss

    # Shapes of the state determine its sharding tree; evaluate
    # abstractly so no host memory is spent.
    def make_jitted(example_batch):
        rng = jax.random.PRNGKey(0)
        abstract_state = jax.eval_shape(_init, rng, example_batch)
        state_sharding = infer_shardings(abstract_state, mesh, rules)
        init_fn = jax.jit(_init, out_shardings=state_sharding)
        step_fn = jax.jit(
            _step,
            in_shardings=(state_sharding,
                          jax.tree.map(lambda _: batch_sharding,
                                       example_batch)),
            out_shardings=(state_sharding, repl),
            donate_argnums=(0,) if donate else ())
        return init_fn, step_fn

    return make_jitted, batch_sharding


def make_bert_batch(batch_size: int, seq_len: int, vocab_size: int,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(0, vocab_size, (batch_size, seq_len),
                            dtype=np.int32)
    labels = rng.randint(0, vocab_size, (batch_size, seq_len),
                         dtype=np.int32)
    mask = (rng.rand(batch_size, seq_len) < 0.15).astype(np.int32)
    return {"input_ids": input_ids, "labels": labels, "mask": mask}


def run_bert_dry_run(n_devices: int, config: Optional[BertConfig] = None,
                     batch_size: int = 8, seq_len: int = 64):
    """One full sharded pretraining step on an ``n_devices`` mesh with
    tiny shapes — the multi-chip compile/execute validation path."""
    from .models.bert import bert_tiny_config
    from .parallel.mesh import build_mesh

    config = config or bert_tiny_config(max_position_embeddings=seq_len)
    axes = factor_mesh_axes(n_devices)
    mesh = build_mesh(axes)
    make_jitted, batch_sharding = make_bert_pretrain_step(config, mesh)
    batch = make_bert_batch(batch_size, seq_len, config.vocab_size)
    batch = jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding), batch)
    init_fn, step_fn = make_jitted(batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    state, loss = step_fn(state, batch)
    jax.block_until_ready(loss)
    return float(loss), mesh
