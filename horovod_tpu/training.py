"""Sharded SPMD training steps for the flagship models.

The TPU-native core training path: one jit-compiled step per model whose
parameters, optimizer state and activations are laid out over a named
mesh (dp / tp / sp / fsdp axes), with XLA inserting the gradient
allreduce and tensor-parallel collectives (GSPMD).  This is what
replaces the reference's DistributedOptimizer+NCCL pipeline at full
performance (reference: torch/optimizer.py:110-236,
tensorflow/__init__.py:334-381 — gradient hooks feeding allreduce); the
drop-in per-gradient API also exists (horovod_tpu.jax) but this is the
path that hits peak MXU/ICI utilisation.
"""

import logging
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .common.jax_compat import shard_map
from .models.bert import BertConfig, BertForMaskedLM, mlm_loss
from .parallel.sharding import (bert_partition_rules, infer_shardings,
                                Rules)


class TrainState(train_state.TrainState):
    pass


def factor_mesh_axes(n_devices: int,
                     names: Tuple[str, ...] = ("dp", "tp", "sp"),
                     absorb: str = "dp") -> Dict[str, int]:
    """Factor a device count into 2s over the named axes, in order.

    8 → first three axes get 2; 4 → first two; 2 → first.  Any
    leftover factor — everything beyond one 2 per axis, plus any odd
    factor — is absorbed into ``absorb`` (the data axis by default:
    dp tolerates any size, while tp/sp must divide model/sequence
    dims).  Examples: 16 → dp=4,tp=2,sp=2; 6 → dp=6; 12 → dp=6,tp=2.

    TPU pods are powers of two, where this is exact; for other device
    counts a warning notes the lopsided absorption so nobody is
    surprised by dp carrying an odd factor.
    """
    if not names:
        raise ValueError("names must be non-empty")
    if absorb not in names:
        raise ValueError(f"absorb={absorb!r} is not one of {names}")
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    axes = {name: 1 for name in names}
    rest = n_devices
    for name in names:
        if rest % 2 == 0:
            axes[name] = 2
            rest //= 2
    axes[absorb] *= rest
    if rest > 1 and rest % 2:
        logging.getLogger("horovod_tpu.training").warning(
            "factor_mesh_axes: %d devices has odd factor %d, absorbed "
            "into %r -> %s; pass an explicit axis dict for a different "
            "layout", n_devices, rest, absorb, axes)
    return axes


def make_bert_pretrain_step(
        config: BertConfig, mesh: Mesh,
        learning_rate: float = 1e-4,
        rules: Optional[Rules] = None,
        donate: bool = True,
        dropout_seed: int = 0,
) -> Tuple[Callable, "NamedSharding"]:
    """Returns ``(make_jitted, batch_sharding)``.

    ``make_jitted(example_batch)`` builds and returns the jit-compiled
    ``(init_fn, step_fn)`` pair for that batch's shapes (shapes are
    needed to lay out the state sharding before compilation);
    ``batch_sharding`` is the NamedSharding inputs must be placed with.

    * params/opt-state sharded by Megatron-style rules (tp [+ fsdp]);
    * batch sharded (dp, sp) over (batch, sequence);
    * dropout active whenever the config's dropout rates are non-zero,
      with the rng folded from the step counter (deterministic replay);
    * gradient reduction over dp and the tp/sp collectives are inserted
      by XLA (GSPMD) — on TPU hardware they ride ICI.
    """
    model = BertForMaskedLM(config)
    tx = optax.adamw(learning_rate, weight_decay=0.01)
    rules = rules or bert_partition_rules(
        tp="tp" if "tp" in mesh.shape else None,
        fsdp="fsdp" if "fsdp" in mesh.shape else None)
    deterministic = (config.hidden_dropout == 0.0
                     and config.attention_dropout == 0.0)

    batch_spec = P("dp" if "dp" in mesh.shape else None,
                   "sp" if "sp" in mesh.shape else None)
    batch_sharding = NamedSharding(mesh, batch_spec)
    repl = NamedSharding(mesh, P())

    def _init(rng, batch):
        params = model.init(rng, batch["input_ids"],
                            deterministic=True)["params"]
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=tx)

    def _loss_fn(params, batch, dropout_rng):
        rngs = None if deterministic else {"dropout": dropout_rng}
        logits = model.apply({"params": params}, batch["input_ids"],
                             attention_mask=batch.get("attention_mask"),
                             deterministic=deterministic, rngs=rngs)
        return mlm_loss(logits, batch["labels"], batch["mask"])

    def _step(state, batch):
        dropout_rng = jax.random.fold_in(
            jax.random.PRNGKey(dropout_seed), state.step)
        loss, grads = jax.value_and_grad(_loss_fn)(
            state.params, batch, dropout_rng)
        new_state = state.apply_gradients(grads=grads)
        return new_state, loss

    # Shapes of the state determine its sharding tree; evaluate
    # abstractly so no host memory is spent.
    def make_jitted(example_batch):
        rng = jax.random.PRNGKey(0)
        abstract_state = jax.eval_shape(_init, rng, example_batch)
        state_sharding = infer_shardings(abstract_state, mesh, rules)
        init_fn = jax.jit(_init, out_shardings=state_sharding)
        step_fn = jax.jit(
            _step,
            in_shardings=(state_sharding,
                          jax.tree.map(lambda _: batch_sharding,
                                       example_batch)),
            out_shardings=(state_sharding, repl),
            donate_argnums=(0,) if donate else ())
        return init_fn, step_fn

    return make_jitted, batch_sharding


def make_bert_batch(batch_size: int, seq_len: int, vocab_size: int,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(0, vocab_size, (batch_size, seq_len),
                            dtype=np.int32)
    labels = rng.randint(0, vocab_size, (batch_size, seq_len),
                         dtype=np.int32)
    mask = (rng.rand(batch_size, seq_len) < 0.15).astype(np.int32)
    return {"input_ids": input_ids, "labels": labels, "mask": mask}


def run_pipeline_moe_dry_run(n_devices: int, microbatches: int = 4,
                             tokens: int = 8, dim: int = 16):
    """One differentiable pipeline-parallel + expert-parallel training
    step on a {pp, ep, dp} mesh with tiny shapes: each pipeline stage is
    dense → Switch-MoE (alltoall over ep) → dense, microbatches stream
    GPipe-style over pp, gradients reduce over dp."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .parallel.mesh import build_mesh
    from .parallel.moe import moe_ffn
    from .parallel.pipeline import pipeline_apply

    axes = factor_mesh_axes(n_devices, names=("pp", "ep", "dp"))
    mesh = build_mesh(axes)
    S, E = axes["pp"], axes["ep"]

    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(S, dim, dim).astype(np.float32) * 0.2)
    gate_w = jnp.asarray(rng.randn(S, dim, E).astype(np.float32))
    expert_W = jnp.asarray(
        rng.randn(S, E, dim, dim).astype(np.float32) * 0.2)
    x = jnp.asarray(rng.randn(
        microbatches, axes["dp"] * tokens, dim).astype(np.float32))

    def expert_fn(W, h):
        return jnp.tanh(h @ W[0])

    def stage(params, h):
        W, gw, eW = params
        h = jnp.tanh(h @ W[0])
        y, _aux = moe_ffn(h, gw[0], expert_fn, eW[0], axis_name="ep",
                          capacity_factor=4.0)
        return h + y

    def loss_fn(Ws, gate_w, expert_W, xm):
        out = pipeline_apply(stage, (Ws, gate_w, expert_W), xm,
                             axis_name="pp", vary_axes=("ep", "dp"))
        return jnp.mean(out ** 2)

    def grads_fn(Ws, gate_w, expert_W, xm):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            Ws, gate_w, expert_W, xm)
        # Gradient data parallelism over dp.
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(g, "dp"), grads)
        return jax.lax.pmean(loss, ("dp", "ep")), grads

    run = jax.jit(shard_map(
        grads_fn, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P("pp", "ep"), P(None, "dp")),
        out_specs=(P(), (P("pp"), P("pp"), P("pp", "ep")))))
    loss, grads = run(Ws, gate_w, expert_W, x)
    jax.block_until_ready(loss)
    return float(loss), mesh


def run_ring_attention_dry_run(n_devices: int, seq_per_dev: int = 8,
                               heads: int = 4, dim: int = 8):
    """Ring attention over an sp-axis mesh: one causal forward+backward
    on a sequence sharded across every device."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .parallel.attention import ring_attention
    from .parallel.mesh import build_mesh

    mesh = build_mesh({"sp": n_devices})
    rng = np.random.RandomState(0)
    S = n_devices * seq_per_dev
    q, k, v = (jnp.asarray(rng.randn(1, S, heads, dim)
                           .astype(np.float32)) for _ in range(3))

    def loss(q, k, v):
        return jnp.mean(
            ring_attention(q, k, v, axis_name="sp", causal=True) ** 2)

    f = jax.jit(shard_map(
        jax.grad(loss), mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp")))
    g = f(q, k, v)
    jax.block_until_ready(g)
    assert not jnp.isnan(jnp.asarray(g)).any(), \
        "ring attention produced NaN gradients"
    return mesh


def run_bert_dry_run(n_devices: int, config: Optional[BertConfig] = None,
                     batch_size: int = 8, seq_len: int = 64):
    """One full sharded pretraining step on an ``n_devices`` mesh with
    tiny shapes — the multi-chip compile/execute validation path."""
    from .models.bert import bert_tiny_config
    from .parallel.mesh import build_mesh

    config = config or bert_tiny_config(max_position_embeddings=seq_len)
    axes = factor_mesh_axes(n_devices)
    mesh = build_mesh(axes)
    make_jitted, batch_sharding = make_bert_pretrain_step(config, mesh)
    batch = make_bert_batch(batch_size, seq_len, config.vocab_size)
    batch = jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding), batch)
    init_fn, step_fn = make_jitted(batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    state, loss = step_fn(state, batch)
    jax.block_until_ready(loss)
    return float(loss), mesh


def make_gpt_train_step(config, mesh, learning_rate: float = 1e-2,
                        fsdp: Optional[str] = None):
    """Sharded dp x tp causal-LM training step for the GPT family —
    the decoder counterpart of make_bert_pretrain_step. Returns
    (init_fn, step_fn, batch_sharding); params/opt state are annotated
    with gpt_partition_rules and XLA inserts the collectives.

    ``fsdp`` names a mesh axis to ZeRO-3-shard parameters and optimizer
    state over; the batch shards along the same axis (that axis IS the
    data axis under FSDP), and XLA turns the annotations into the
    all-gather-on-use / reduce-scatter-of-grads schedule (SURVEY §2.3:
    reduce-scatter is the FSDP building block the reference never
    exposed)."""
    import optax
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .models.gpt import GPTLMHeadModel, lm_loss
    from .parallel.sharding import gpt_partition_rules, infer_shardings

    model = GPTLMHeadModel(config)
    tx = optax.adam(learning_rate)
    batch_axis = fsdp or "dp"
    batch_sharding = NamedSharding(mesh, P(batch_axis, None))
    rules = gpt_partition_rules(fsdp=fsdp)

    def init_fn(rng, ids):
        params = model.init(rng, ids)["params"]
        params = jax.tree.map(
            jax.device_put, params,
            infer_shardings(params, mesh, rules))
        return params, tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, ids):
        def loss_fn(p):
            return lm_loss(model.apply({"params": p}, ids), ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return init_fn, step_fn, batch_sharding


def run_gpt_fsdp_dry_run(n_devices: int, batch_size: int = 8,
                         seq_len: int = 16):
    """One fsdp x tp ZeRO-3-sharded causal-LM training step: params and
    optimizer state shard over the fsdp axis, the batch rides the same
    axis, gradients reduce-scatter.  Validates the FSDP schedule
    compiles and executes on an ``n_devices`` mesh."""
    from .models.gpt import gpt_tiny_config
    from .parallel.mesh import build_mesh

    cfg = gpt_tiny_config()
    tp = 2 if n_devices % 2 == 0 else 1
    fsdp = n_devices // tp
    mesh = build_mesh({"fsdp": fsdp, "tp": tp})
    batch_size = -(-max(batch_size, 2 * fsdp) // fsdp) * fsdp
    ids = jax.random.randint(jax.random.PRNGKey(0),
                             (batch_size, seq_len), 0, cfg.vocab_size)
    init_fn, step_fn, batch_sharding = make_gpt_train_step(
        cfg, mesh, fsdp="fsdp")
    ids = jax.device_put(ids, batch_sharding)
    params, opt_state = init_fn(jax.random.PRNGKey(1), ids)
    params, opt_state, loss = step_fn(params, opt_state, ids)
    jax.block_until_ready(loss)
    return float(loss), mesh


def run_gpt_dry_run(n_devices: int, batch_size: int = 8,
                    seq_len: int = 16):
    """One dp x tp sharded causal-LM training step on an ``n_devices``
    mesh with tiny shapes (decoder-family multi-chip validation)."""
    from .models.gpt import gpt_tiny_config
    from .parallel.mesh import build_mesh

    cfg = gpt_tiny_config()
    axes = factor_mesh_axes(n_devices)
    dp = axes["dp"] * axes.get("sp", 1)
    mesh = build_mesh({"dp": dp, "tp": axes.get("tp", 1)})
    # Round the batch UP to a multiple of the dp axis so sharding
    # divides at any device count (dp=3 must not see batch 8).
    batch_size = -(-max(batch_size, 2 * dp) // dp) * dp
    ids = jax.random.randint(jax.random.PRNGKey(0),
                             (batch_size, seq_len), 0, cfg.vocab_size)
    init_fn, step_fn, batch_sharding = make_gpt_train_step(cfg, mesh)
    ids = jax.device_put(ids, batch_sharding)
    params, opt_state = init_fn(jax.random.PRNGKey(1), ids)
    params, opt_state, loss = step_fn(params, opt_state, ids)
    jax.block_until_ready(loss)
    return float(loss), mesh
