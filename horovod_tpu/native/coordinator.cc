// Native rank-0 coordinator: TCP negotiation server.
//
// The C++ equivalent of the reference's C++ controller/background core
// (reference: common/controller.cc ComputeResponseList/:471-748
// ConstructResponse/:777-914 FuseResponses + the transport loops of
// mpi_controller.cc / gloo_controller.cc), rebuilt for the TPU
// framework's event-driven TCP protocol.  Speaks the exact wire format
// of horovod_tpu/common/message.py, so Python workers connect to it
// unchanged; the Python CoordinatorServer remains as a fallback when
// the shared library is unavailable.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread coordinator.cc
//            -o libhvdtpu_coord.so
//
// C API (ctypes):
//   void* hvd_coord_create(int size, const char* bind_addr, int port,
//                          long long fusion_threshold, int elastic,
//                          int allow_ephemeral);     // NULL on failure
//   int   hvd_coord_port(void*);
//   void  hvd_coord_set_fusion(void*, long long);
//   void  hvd_coord_stats(void*, long long* rounds, long long* bytes);
//   void  hvd_coord_stop(void*);

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// wire protocol (mirrors message.py exactly; little-endian, packed)
// ---------------------------------------------------------------------
enum ReqType : int32_t {
  REQ_ALLREDUCE = 0, REQ_ALLGATHER = 1, REQ_BROADCAST = 2, REQ_JOIN = 3,
  REQ_ADASUM = 4, REQ_ALLTOALL = 5, REQ_REDUCESCATTER = 6,
  REQ_BARRIER = 7,
};
enum RespType : int32_t {
  RESP_ALLREDUCE = 0, RESP_ALLGATHER = 1, RESP_BROADCAST = 2,
  RESP_JOIN = 3, RESP_ADASUM = 4, RESP_ALLTOALL = 5,
  RESP_REDUCESCATTER = 6, RESP_BARRIER = 7, RESP_ERROR = 8,
};

const int kDtypeSize[] = {1, 1, 2, 2, 4, 8, 2, 4, 8, 1, 2};

struct Request {
  int32_t rank = 0;
  int32_t type = 0;
  int32_t dtype = 7;
  int32_t root = -1;
  int32_t device = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t psid = 0;
  std::vector<int64_t> shape;
  std::string name;
  std::string op;
  std::vector<int32_t> psr;  // process-set member ranks
};

struct Response {
  int32_t type = 0;
  int32_t dtype = 7;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t psid = 0;
  int32_t root = -1;
  int32_t last_joined = -1;
  std::vector<std::string> names;
  std::vector<int64_t> sizes;
  std::string error;
  std::string op = "Sum";
  std::vector<std::vector<int64_t>> shapes;
  std::vector<int32_t> psr;
};

class Reader {
 public:
  Reader(const uint8_t* d, size_t n) : d_(d), n_(n) {}
  template <typename T> T get() {
    T v;
    std::memcpy(&v, d_ + off_, sizeof(T));
    off_ += sizeof(T);
    return v;
  }
  std::string str(size_t len) {
    std::string s(reinterpret_cast<const char*>(d_ + off_), len);
    off_ += len;
    return s;
  }
  bool ok(size_t need) const { return off_ + need <= n_; }
  size_t off() const { return off_; }

 private:
  const uint8_t* d_;
  size_t n_;
  size_t off_ = 0;
};

class Writer {
 public:
  template <typename T> void put(T v) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
    buf_.insert(buf_.end(), p, p + sizeof(T));
  }
  void str(const std::string& s) {
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  std::vector<uint8_t>& data() { return buf_; }

 private:
  std::vector<uint8_t> buf_;
};

bool parse_request(const uint8_t* d, size_t n, Request* r) {
  // head "<iiiiiddiiHHH" = 50 bytes
  if (n < 50) return false;
  Reader rd(d, n);
  r->rank = rd.get<int32_t>();
  r->type = rd.get<int32_t>();
  r->dtype = rd.get<int32_t>();
  r->root = rd.get<int32_t>();
  r->device = rd.get<int32_t>();
  r->prescale = rd.get<double>();
  r->postscale = rd.get<double>();
  r->psid = rd.get<int32_t>();
  int32_t ndim = rd.get<int32_t>();
  uint16_t name_len = rd.get<uint16_t>();
  uint16_t op_len = rd.get<uint16_t>();
  uint16_t n_psr = rd.get<uint16_t>();
  if (!rd.ok(size_t(ndim) * 8 + name_len + op_len + size_t(n_psr) * 4))
    return false;
  r->shape.resize(ndim);
  for (int i = 0; i < ndim; ++i) r->shape[i] = rd.get<int64_t>();
  r->name = rd.str(name_len);
  r->op = rd.str(op_len);
  r->psr.resize(n_psr);
  for (int i = 0; i < n_psr; ++i) r->psr[i] = rd.get<int32_t>();
  return true;
}

std::vector<uint8_t> serialize_response(const Response& r) {
  Writer w;
  w.put<int32_t>(r.type);
  w.put<int32_t>(r.dtype);
  w.put<double>(r.prescale);
  w.put<double>(r.postscale);
  w.put<int32_t>(r.psid);
  w.put<int32_t>(r.root);
  w.put<int32_t>(r.last_joined);
  w.put<uint16_t>(uint16_t(r.names.size()));
  w.put<uint16_t>(uint16_t(r.sizes.size()));
  w.put<uint16_t>(uint16_t(r.error.size()));
  w.put<uint16_t>(uint16_t(r.op.size()));
  w.put<uint16_t>(uint16_t(r.shapes.size()));
  w.put<uint16_t>(uint16_t(r.psr.size()));
  for (const auto& n : r.names) {
    w.put<uint16_t>(uint16_t(n.size()));
    w.str(n);
  }
  for (int64_t s : r.sizes) w.put<int64_t>(s);
  w.str(r.error);
  w.str(r.op);
  for (const auto& sh : r.shapes) {
    w.put<uint16_t>(uint16_t(sh.size()));
    for (int64_t d : sh) w.put<int64_t>(d);
  }
  for (int32_t p : r.psr) w.put<int32_t>(p);
  return std::move(w.data());
}

std::vector<uint8_t> pack_response_list(const std::vector<Response>& rs) {
  Writer w;
  w.put<uint8_t>(0);  // shutdown flag
  w.put<uint32_t>(uint32_t(rs.size()));
  for (const auto& r : rs) {
    auto b = serialize_response(r);
    w.put<uint32_t>(uint32_t(b.size()));
    w.data().insert(w.data().end(), b.begin(), b.end());
  }
  return std::move(w.data());
}

// ---------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------
bool send_all(int fd, const uint8_t* d, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::send(fd, d + off, n - off, MSG_NOSIGNAL);
    if (k <= 0) return false;
    off += size_t(k);
  }
  return true;
}

bool send_frame(int fd, const char magic[2],
                const std::vector<uint8_t>& payload) {
  uint8_t head[6];
  head[0] = magic[0];
  head[1] = magic[1];
  uint32_t len = uint32_t(payload.size());
  std::memcpy(head + 2, &len, 4);
  if (!send_all(fd, head, 6)) return false;
  return send_all(fd, payload.data(), payload.size());
}

bool recv_exact(int fd, uint8_t* d, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::recv(fd, d + off, n - off, 0);
    if (k <= 0) return false;
    off += size_t(k);
  }
  return true;
}

bool recv_frame(int fd, std::vector<uint8_t>* payload) {
  uint8_t head[6];
  if (!recv_exact(fd, head, 6)) return false;
  uint32_t len;
  std::memcpy(&len, head + 2, 4);
  if (len > (256u << 20)) return false;  // sanity bound
  payload->resize(len);
  return len == 0 || recv_exact(fd, payload->data(), len);
}

// ---------------------------------------------------------------------
// negotiation logic (mirrors controller.py + controller_net.py)
// ---------------------------------------------------------------------
const std::set<int32_t> kFusable = {RESP_ALLREDUCE, RESP_ADASUM,
                                    RESP_ALLGATHER, RESP_REDUCESCATTER};

Response construct_response(const std::string& name,
                            const std::vector<Request>& msgs, int size) {
  const Request& first = msgs[0];
  std::string err;
  for (size_t i = 1; i < msgs.size() && err.empty(); ++i) {
    const Request& m = msgs[i];
    if (m.type != first.type)
      err = "Mismatched collective operations for tensor " + name + ".";
    else if (m.dtype != first.dtype)
      err = "Mismatched data types for tensor " + name + ".";
    else if (m.op != first.op)
      err = "Mismatched reduction ops for tensor " + name + ".";
    else if (m.prescale != first.prescale ||
             m.postscale != first.postscale)
      err = "Mismatched prescale/postscale factors for tensor " + name +
            ".";
    else if (first.type == REQ_BROADCAST && m.root != first.root)
      err = "Mismatched broadcast root ranks for tensor " + name + ".";
    else if ((first.type == REQ_ALLREDUCE || first.type == REQ_ADASUM ||
              first.type == REQ_BROADCAST) &&
             m.shape != first.shape)
      err = "Mismatched shapes for tensor " + name + ".";
    else if (first.type == REQ_ALLGATHER ||
             first.type == REQ_ALLTOALL ||
             first.type == REQ_REDUCESCATTER) {
      if (m.shape.size() != first.shape.size() ||
          (m.shape.size() > 1 &&
           !std::equal(m.shape.begin() + 1, m.shape.end(),
                       first.shape.begin() + 1)))
        err = "Mismatched non-first dimensions for tensor " + name + ".";
    }
  }
  if (!err.empty()) {
    Response r;
    r.type = RESP_ERROR;
    r.names = {name};
    r.error = err;
    r.psid = first.psid;
    return r;
  }
  Response r;
  r.type = first.type;  // enum values align 1:1
  r.names = {name};
  r.dtype = first.dtype;
  r.prescale = first.prescale;
  r.postscale = first.postscale;
  r.psid = first.psid;
  r.root = first.root;
  r.op = first.op;
  r.shapes = {first.shape};
  r.psr = first.psr;
  if (first.type == REQ_ALLGATHER) {
    std::map<int32_t, const Request*> by_rank;
    for (const auto& m : msgs) by_rank[m.rank] = &m;
    for (int rk = 0; rk < size; ++rk) {
      auto it = by_rank.find(rk);
      if (it != by_rank.end()) {
        const auto& sh = it->second->shape;
        r.sizes.push_back(sh.empty() ? 1 : sh[0]);
      } else {
        r.sizes.push_back(0);  // joined (departed) rank: zero rows
      }
    }
  }
  return r;
}

class Coordinator {
 public:
  Coordinator(int size, const std::string& bind_addr, int port,
              int64_t fusion_threshold, bool elastic,
              bool allow_ephemeral)
      : size_(size),
        fusion_threshold_(fusion_threshold),
        elastic_(elastic) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(uint16_t(port));
    addr.sin_addr.s_addr =
        bind_addr.empty() ? INADDR_ANY : ::inet_addr(bind_addr.c_str());
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      if (!allow_ephemeral) {
        ::close(listen_fd_);
        listen_fd_ = -1;
        return;
      }
      addr.sin_port = 0;
      if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
                 sizeof(addr)) != 0) {
        ::close(listen_fd_);
        listen_fd_ = -1;
        return;
      }
    }
    ::listen(listen_fd_, size + 4);
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&bound), &blen);
    port_ = ntohs(bound.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  bool valid() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void set_fusion(int64_t v) { fusion_threshold_.store(v); }

  void stats(int64_t* rounds, int64_t* bytes) {
    *rounds = rounds_.load();
    *bytes = bytes_.load();
  }

  void Stop() {
    if (stop_.exchange(true)) return;  // idempotent (also ~Coordinator)
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : conns_) {
        ::shutdown(kv.second, SHUT_RDWR);
        ::close(kv.second);
      }
      conns_.clear();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : rank_threads_)
      if (t.joinable()) t.join();
  }

  ~Coordinator() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      pollfd pfd{listen_fd_, POLLIN, 0};
      int rc = ::poll(&pfd, 1, 500);
      if (stop_.load()) return;
      if (rc <= 0) continue;
      int conn = ::accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) continue;
      int one = 1;
      ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // First frame: rank id.
      std::vector<uint8_t> payload;
      if (!recv_frame(conn, &payload) || payload.size() < 4) {
        ::close(conn);
        continue;
      }
      int32_t rank;
      std::memcpy(&rank, payload.data(), 4);
      {
        std::lock_guard<std::mutex> g(mu_);
        conns_[rank] = conn;
      }
      {
        std::lock_guard<std::mutex> g(departed_mu_);
        ++seen_;
      }
      rank_threads_.emplace_back(
          [this, rank, conn] { RankLoop(rank, conn); });
    }
  }

  void RankLoop(int rank, int conn) {
    bool clean = false;
    std::vector<uint8_t> payload;
    while (!stop_.load()) {
      if (!recv_frame(conn, &payload)) break;
      if (payload.size() < 5) break;
      uint8_t shutdown_flag = payload[0];
      if (shutdown_flag) {
        clean = true;
        break;
      }
      uint32_t count;
      std::memcpy(&count, payload.data() + 1, 4);
      std::vector<Request> reqs;
      size_t off = 5;
      bool ok = true;
      for (uint32_t i = 0; i < count && ok; ++i) {
        if (off + 4 > payload.size()) {
          ok = false;
          break;
        }
        uint32_t len;
        std::memcpy(&len, payload.data() + off, 4);
        off += 4;
        if (off + len > payload.size()) {
          ok = false;
          break;
        }
        Request r;
        if (!parse_request(payload.data() + off, len, &r)) {
          ok = false;
          break;
        }
        off += len;
        reqs.push_back(std::move(r));
      }
      if (!ok) break;
      HandleRequests(rank, reqs);
    }
    {
      std::lock_guard<std::mutex> g(departed_mu_);
      ++departed_;
      departed_cv_.notify_all();
    }
    if (!stop_.load()) OnRankLost(rank, clean);
  }

 public:
  void DepartureCounts(int* seen, int* departed) {
    std::lock_guard<std::mutex> g(departed_mu_);
    *seen = seen_;
    *departed = departed_;
  }

 private:

  int RequiredFor(const Request& r) const {
    return r.psr.empty() ? size_ : int(r.psr.size());
  }

  int JoinedCountFor(const Request& r) const {
    if (r.psr.empty()) return int(joined_.size());
    int c = 0;
    for (int32_t p : r.psr)
      if (joined_.count(p)) ++c;
    return c;
  }

  // Tensors waiting only on joined (departed) ranks became complete.
  void ScanComplete(std::vector<Response>* ready) {
    std::vector<std::string> done;
    for (auto& kv : table_) {
      if (kv.second.empty()) continue;
      const Request& first = kv.second[0];
      int required = RequiredFor(first);
      if (int(kv.second.size()) + JoinedCountFor(first) >= required) {
        ready->push_back(
            construct_response(kv.first, kv.second, size_));
        done.push_back(kv.first);
      }
    }
    for (const auto& n : done) table_.erase(n);
  }

  int64_t ResponseBytes(const Response& r) {
    int64_t total = 0;
    for (const auto& n : r.names) {
      auto it = elem_cache_.find(n);
      int64_t elems = it == elem_cache_.end() ? 0 : it->second;
      total += elems * kDtypeSize[r.dtype];
    }
    return total;
  }

  bool CanFuse(const Response& a, const Response& b) {
    if (a.type != b.type) return false;
    if (!kFusable.count(a.type)) return false;
    return a.dtype == b.dtype && a.psid == b.psid &&
           a.prescale == b.prescale && a.postscale == b.postscale &&
           a.op == b.op;
  }

  // Greedy fusion with look-ahead skip (fusion.py / reference
  // controller.cc:777-914).
  std::vector<Response> Fuse(std::vector<Response> queue) {
    std::vector<Response> out;
    int64_t threshold = fusion_threshold_.load();
    while (!queue.empty()) {
      Response base = std::move(queue.front());
      queue.erase(queue.begin());
      if (!kFusable.count(base.type)) {
        out.push_back(std::move(base));
        continue;
      }
      int64_t acc = ResponseBytes(base);
      size_t i = 0;
      while (i < queue.size()) {
        Response& cand = queue[i];
        if (CanFuse(base, cand)) {
          int64_t cb = ResponseBytes(cand);
          if (acc + cb <= threshold) {
            base.names.insert(base.names.end(), cand.names.begin(),
                              cand.names.end());
            base.sizes.insert(base.sizes.end(), cand.sizes.begin(),
                              cand.sizes.end());
            base.shapes.insert(base.shapes.end(), cand.shapes.begin(),
                               cand.shapes.end());
            acc += cb;
            queue.erase(queue.begin() + i);
            continue;
          }
          break;  // full; keep remaining order intact
        }
        ++i;  // look-ahead skip
      }
      out.push_back(std::move(base));
    }
    return out;
  }

  void BroadcastLocked(const std::vector<Response>& responses) {
    auto payload = pack_response_list(responses);
    std::vector<int> dead;
    for (auto& kv : conns_) {
      if (!send_frame(kv.second, "RS", payload)) dead.push_back(kv.first);
    }
    for (int r : dead) {
      ::close(conns_[r]);
      conns_.erase(r);
    }
  }

  void HandleRequests(int rank, const std::vector<Request>& reqs) {
    std::lock_guard<std::mutex> g(mu_);
    if (broken_) {
      std::vector<Response> errs;
      for (const auto& req : reqs) {
        Response r;
        r.type = RESP_ERROR;
        r.names = {req.name};
        r.error = "membership changed; collective cannot complete";
        errs.push_back(std::move(r));
      }
      if (!errs.empty()) BroadcastLocked(errs);
      return;
    }
    std::vector<Response> ready;
    for (const auto& req : reqs) {
      int64_t n = 1;
      for (int64_t d : req.shape) n *= d;
      elem_cache_[req.name] = n;
      if (req.type == REQ_JOIN) {
        joined_.insert(rank);
        last_joined_ = rank;
        if (int(joined_.size()) == size_) {
          Response r;
          r.type = RESP_JOIN;
          r.names = {"join"};
          r.last_joined = last_joined_;
          ready.push_back(std::move(r));
          joined_.clear();
        } else {
          ScanComplete(&ready);
        }
        continue;
      }
      if (req.type == REQ_BARRIER) {
        int required = RequiredFor(req);
        auto& arrived = barriers_[req.name];
        arrived.insert(rank);
        if (int(arrived.size()) >= required) {
          barriers_.erase(req.name);
          Response r;
          r.type = RESP_BARRIER;
          r.names = {req.name};
          r.psid = req.psid;
          r.psr = req.psr;
          ready.push_back(std::move(r));
        }
        continue;
      }
      int required = RequiredFor(req);
      auto& msgs = table_[req.name];
      msgs.push_back(req);
      if (int(msgs.size()) + JoinedCountFor(req) >= required) {
        ready.push_back(construct_response(req.name, msgs, size_));
        table_.erase(req.name);
      }
    }
    if (ready.empty()) return;
    auto fused = Fuse(std::move(ready));
    BroadcastLocked(fused);
    int64_t nbytes = 0;
    for (const auto& r : fused) nbytes += ResponseBytes(r);
    rounds_.fetch_add(1);
    bytes_.fetch_add(nbytes);
  }

  void OnRankLost(int rank, bool clean) {
    if (!elastic_) return;
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(rank);
    if (it != conns_.end()) {
      ::close(it->second);
      conns_.erase(it);
    }
    broken_ = true;
    std::vector<Response> errs;
    std::string msg = "rank " + std::to_string(rank) +
                      " left the job (" +
                      (clean ? "clean" : "connection lost") +
                      "); membership changed";
    for (auto& kv : table_) {
      Response r;
      r.type = RESP_ERROR;
      r.names = {kv.first};
      r.error = msg;
      errs.push_back(std::move(r));
    }
    for (auto& kv : barriers_) {
      Response r;
      r.type = RESP_ERROR;
      r.names = {kv.first};
      r.error = msg;
      errs.push_back(std::move(r));
    }
    table_.clear();
    barriers_.clear();
    if (!errs.empty()) BroadcastLocked(errs);
  }

  int size_;
  std::atomic<int64_t> fusion_threshold_;
  bool elastic_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stop_{false};
  std::thread accept_thread_;
  std::vector<std::thread> rank_threads_;

  std::mutex mu_;
  std::map<int, int> conns_;                      // rank -> fd
  std::map<std::string, std::vector<Request>> table_;
  std::map<std::string, std::set<int>> barriers_;
  std::map<std::string, int64_t> elem_cache_;
  std::set<int> joined_;
  int last_joined_ = -1;
  bool broken_ = false;
  std::mutex departed_mu_;
  std::condition_variable departed_cv_;
  int seen_ = 0;
  int departed_ = 0;
  std::atomic<int64_t> rounds_{0};
  std::atomic<int64_t> bytes_{0};
};

}  // namespace

extern "C" {

void* hvd_coord_create(int size, const char* bind_addr, int port,
                       long long fusion_threshold, int elastic,
                       int allow_ephemeral) {
  auto* c = new Coordinator(size, bind_addr ? bind_addr : "", port,
                            fusion_threshold, elastic != 0,
                            allow_ephemeral != 0);
  if (!c->valid()) {
    delete c;
    return nullptr;
  }
  return c;
}

int hvd_coord_port(void* h) {
  return static_cast<Coordinator*>(h)->port();
}

void hvd_coord_set_fusion(void* h, long long v) {
  static_cast<Coordinator*>(h)->set_fusion(v);
}

void hvd_coord_stats(void* h, long long* rounds, long long* bytes) {
  int64_t r, b;
  static_cast<Coordinator*>(h)->stats(&r, &b);
  *rounds = r;
  *bytes = b;
}

void hvd_coord_counts(void* h, int* seen, int* departed) {
  static_cast<Coordinator*>(h)->DepartureCounts(seen, departed);
}

void hvd_coord_stop(void* h) {
  auto* c = static_cast<Coordinator*>(h);
  c->Stop();
  delete c;
}

}  // extern "C"
