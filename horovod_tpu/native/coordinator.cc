// Native rank-0 coordinator: TCP negotiation server.
//
// The C++ equivalent of the reference's C++ controller/background core
// (reference: common/controller.cc ComputeResponseList/:471-748
// ConstructResponse/:777-914 FuseResponses + the transport loops of
// mpi_controller.cc / gloo_controller.cc), rebuilt for the TPU
// framework's event-driven TCP protocol.  Speaks the exact wire format
// of horovod_tpu/common/message.py, so Python workers connect to it
// unchanged; the Python CoordinatorServer remains as a fallback when
// the shared library is unavailable.
//
// Implements the response-cache fast path (reference:
// response_cache.{h,cc}, fast path controller.cc:81-236) with
// coordinator-authoritative bit assignment: steady-state steps exchange
// 4-byte cache bits (CH uplink / CB downlink) instead of full
// request/response lists.  Also: group-atomic fusion (reference
// group_table.{h,cc}, controller.cc:199-223) and rank-0 stall
// attribution (reference stall_inspector.h:74-80).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread coordinator.cc
//            -o libhvdtpu_coord.so
//
// C API (ctypes):
//   void* hvd_coord_create(int size, const char* bind_addr, int port,
//                          long long fusion_threshold, int elastic,
//                          int allow_ephemeral, int cache_capacity,
//                          double stall_warn_s, double stall_shutdown_s);
//   int   hvd_coord_port(void*);
//   void  hvd_coord_set_fusion(void*, long long);
//   void  hvd_coord_stats(void*, long long* rounds, long long* bytes);
//   void  hvd_coord_cache_stats(void*, long long* fast_rounds,
//                               long long* full_rounds);
//   int   hvd_coord_drain_round_bytes(void*, long long* out, int cap);
//   int   hvd_coord_stall_report(void*, char* buf, int cap);
//   void  hvd_coord_counts(void*, int* seen, int* departed);
//   void  hvd_coord_stop(void*);

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// wire protocol (mirrors message.py exactly; little-endian, packed)
// ---------------------------------------------------------------------
enum ReqType : int32_t {
  REQ_ALLREDUCE = 0, REQ_ALLGATHER = 1, REQ_BROADCAST = 2, REQ_JOIN = 3,
  REQ_ADASUM = 4, REQ_ALLTOALL = 5, REQ_REDUCESCATTER = 6,
  REQ_BARRIER = 7,
};
enum RespType : int32_t {
  RESP_ALLREDUCE = 0, RESP_ALLGATHER = 1, RESP_BROADCAST = 2,
  RESP_JOIN = 3, RESP_ADASUM = 4, RESP_ALLTOALL = 5,
  RESP_REDUCESCATTER = 6, RESP_BARRIER = 7, RESP_ERROR = 8,
};

const int kDtypeSize[] = {1, 1, 2, 2, 4, 8, 2, 4, 8, 1, 2};

struct Request {
  int32_t rank = 0;
  int32_t type = 0;
  int32_t dtype = 7;
  int32_t root = -1;
  int32_t device = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t psid = 0;
  int32_t group_id = -1;
  std::vector<int64_t> shape;
  std::string name;
  std::string op;
  std::vector<int32_t> psr;  // process-set member ranks
  // Alltoall send splits (group order) — assembled into the response's
  // sizes matrix so the data plane skips its own split exchange
  // (mirrors message.py Request.splits; reference
  // AlltoallGetRecvSplits, mpi_controller.cc:212-223).
  std::vector<int64_t> splits;
};

struct Response {
  int32_t type = 0;
  int32_t dtype = 7;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t psid = 0;
  int32_t root = -1;
  int32_t last_joined = -1;
  std::vector<std::string> names;
  std::vector<int64_t> sizes;
  std::string error;
  std::string op = "Sum";
  std::vector<std::vector<int64_t>> shapes;
  std::vector<int32_t> psr;
  std::vector<int32_t> cache_bits;
};

class Reader {
 public:
  Reader(const uint8_t* d, size_t n) : d_(d), n_(n) {}
  template <typename T> T get() {
    T v;
    std::memcpy(&v, d_ + off_, sizeof(T));
    off_ += sizeof(T);
    return v;
  }
  std::string str(size_t len) {
    std::string s(reinterpret_cast<const char*>(d_ + off_), len);
    off_ += len;
    return s;
  }
  bool ok(size_t need) const { return off_ + need <= n_; }
  size_t off() const { return off_; }

 private:
  const uint8_t* d_;
  size_t n_;
  size_t off_ = 0;
};

class Writer {
 public:
  template <typename T> void put(T v) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
    buf_.insert(buf_.end(), p, p + sizeof(T));
  }
  void str(const std::string& s) {
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  std::vector<uint8_t>& data() { return buf_; }

 private:
  std::vector<uint8_t> buf_;
};

bool parse_request(const uint8_t* d, size_t n, Request* r) {
  // head "<iiiiiddiiiHHHH" = 56 bytes
  if (n < 56) return false;
  Reader rd(d, n);
  r->rank = rd.get<int32_t>();
  r->type = rd.get<int32_t>();
  r->dtype = rd.get<int32_t>();
  r->root = rd.get<int32_t>();
  r->device = rd.get<int32_t>();
  r->prescale = rd.get<double>();
  r->postscale = rd.get<double>();
  r->psid = rd.get<int32_t>();
  r->group_id = rd.get<int32_t>();
  int32_t ndim = rd.get<int32_t>();
  uint16_t name_len = rd.get<uint16_t>();
  uint16_t op_len = rd.get<uint16_t>();
  uint16_t n_psr = rd.get<uint16_t>();
  uint16_t n_splits = rd.get<uint16_t>();
  if (!rd.ok(size_t(ndim) * 8 + name_len + op_len +
             size_t(n_psr) * 4 + size_t(n_splits) * 8))
    return false;
  r->shape.resize(ndim);
  for (int i = 0; i < ndim; ++i) r->shape[i] = rd.get<int64_t>();
  r->name = rd.str(name_len);
  r->op = rd.str(op_len);
  r->psr.resize(n_psr);
  for (int i = 0; i < n_psr; ++i) r->psr[i] = rd.get<int32_t>();
  r->splits.resize(n_splits);
  for (int i = 0; i < n_splits; ++i) r->splits[i] = rd.get<int64_t>();
  return true;
}

std::vector<uint8_t> serialize_response(const Response& r) {
  Writer w;
  w.put<int32_t>(r.type);
  w.put<int32_t>(r.dtype);
  w.put<double>(r.prescale);
  w.put<double>(r.postscale);
  w.put<int32_t>(r.psid);
  w.put<int32_t>(r.root);
  w.put<int32_t>(r.last_joined);
  w.put<uint16_t>(uint16_t(r.names.size()));
  // uint32: alltoall piggybacks a group^2 split matrix here, which
  // overflows uint16 at 256-rank groups (mirrors message.py "<...I...>").
  w.put<uint32_t>(uint32_t(r.sizes.size()));
  w.put<uint16_t>(uint16_t(r.error.size()));
  w.put<uint16_t>(uint16_t(r.op.size()));
  w.put<uint16_t>(uint16_t(r.shapes.size()));
  w.put<uint16_t>(uint16_t(r.psr.size()));
  w.put<uint16_t>(uint16_t(r.cache_bits.size()));
  for (const auto& n : r.names) {
    w.put<uint16_t>(uint16_t(n.size()));
    w.str(n);
  }
  for (int64_t s : r.sizes) w.put<int64_t>(s);
  w.str(r.error);
  w.str(r.op);
  for (const auto& sh : r.shapes) {
    w.put<uint16_t>(uint16_t(sh.size()));
    for (int64_t d : sh) w.put<int64_t>(d);
  }
  for (int32_t p : r.psr) w.put<int32_t>(p);
  for (int32_t b : r.cache_bits) w.put<int32_t>(b);
  return std::move(w.data());
}

std::vector<uint8_t> pack_response_list(const std::vector<Response>& rs) {
  Writer w;
  w.put<uint8_t>(0);  // shutdown flag
  w.put<uint32_t>(uint32_t(rs.size()));
  for (const auto& r : rs) {
    auto b = serialize_response(r);
    w.put<uint32_t>(uint32_t(b.size()));
    w.data().insert(w.data().end(), b.begin(), b.end());
  }
  return std::move(w.data());
}

std::vector<uint8_t> pack_bits(const std::vector<int32_t>& bits) {
  Writer w;
  w.put<uint32_t>(uint32_t(bits.size()));
  for (int32_t b : bits) w.put<uint32_t>(uint32_t(b));
  return std::move(w.data());
}

std::vector<uint8_t> pack_bit_batches(
    const std::vector<std::vector<int32_t>>& batches) {
  Writer w;
  w.put<uint32_t>(uint32_t(batches.size()));
  for (const auto& batch : batches) {
    w.put<uint32_t>(uint32_t(batch.size()));
    for (int32_t b : batch) w.put<uint32_t>(uint32_t(b));
  }
  return std::move(w.data());
}

bool unpack_bits(const uint8_t* d, size_t n, std::vector<int32_t>* out) {
  if (n < 4) return false;
  uint32_t count;
  std::memcpy(&count, d, 4);
  if (n < 4 + size_t(count) * 4) return false;
  out->resize(count);
  for (uint32_t i = 0; i < count; ++i) {
    uint32_t v;
    std::memcpy(&v, d + 4 + i * 4, 4);
    (*out)[i] = int32_t(v);
  }
  return true;
}

// ---------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------
bool send_all(int fd, const uint8_t* d, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::send(fd, d + off, n - off, MSG_NOSIGNAL);
    if (k <= 0) return false;
    off += size_t(k);
  }
  return true;
}

bool send_frame(int fd, const char magic[2],
                const std::vector<uint8_t>& payload) {
  uint8_t head[6];
  head[0] = magic[0];
  head[1] = magic[1];
  uint32_t len = uint32_t(payload.size());
  std::memcpy(head + 2, &len, 4);
  if (!send_all(fd, head, 6)) return false;
  return send_all(fd, payload.data(), payload.size());
}

bool recv_exact(int fd, uint8_t* d, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::recv(fd, d + off, n - off, 0);
    if (k <= 0) return false;
    off += size_t(k);
  }
  return true;
}

bool recv_frame(int fd, char magic[2], std::vector<uint8_t>* payload) {
  uint8_t head[6];
  if (!recv_exact(fd, head, 6)) return false;
  magic[0] = char(head[0]);
  magic[1] = char(head[1]);
  uint32_t len;
  std::memcpy(&len, head + 2, 4);
  if (len > (256u << 20)) return false;  // sanity bound
  payload->resize(len);
  return len == 0 || recv_exact(fd, payload->data(), len);
}

// ---------------------------------------------------------------------
// negotiation logic (mirrors controller.py + controller_net.py)
// ---------------------------------------------------------------------
const std::set<int32_t> kFusable = {RESP_ALLREDUCE, RESP_ADASUM,
                                    RESP_ALLGATHER, RESP_REDUCESCATTER};
// ALLTOALL is excluded (round 5): its response carries the send-split
// matrix, and splits may change call-to-call under an unchanged
// signature — a cached response would serve stale recv splits
// (mirrors response_cache.py CACHEABLE).
const std::set<int32_t> kCacheable = {RESP_ALLREDUCE, RESP_ADASUM,
                                      RESP_ALLGATHER, RESP_BROADCAST,
                                      RESP_REDUCESCATTER};

Response construct_response(const std::string& name,
                            const std::vector<Request>& msgs, int size) {
  const Request& first = msgs[0];
  std::string err;
  for (size_t i = 1; i < msgs.size() && err.empty(); ++i) {
    const Request& m = msgs[i];
    if (m.type != first.type)
      err = "Mismatched collective operations for tensor " + name + ".";
    else if (m.dtype != first.dtype)
      err = "Mismatched data types for tensor " + name + ".";
    else if (m.op != first.op)
      err = "Mismatched reduction ops for tensor " + name + ".";
    else if (m.prescale != first.prescale ||
             m.postscale != first.postscale)
      err = "Mismatched prescale/postscale factors for tensor " + name +
            ".";
    else if (first.type == REQ_BROADCAST && m.root != first.root)
      err = "Mismatched broadcast root ranks for tensor " + name + ".";
    else if ((first.type == REQ_ALLREDUCE || first.type == REQ_ADASUM ||
              first.type == REQ_BROADCAST) &&
             m.shape != first.shape)
      err = "Mismatched shapes for tensor " + name + ".";
    else if (first.type == REQ_ALLGATHER ||
             first.type == REQ_ALLTOALL ||
             first.type == REQ_REDUCESCATTER) {
      if (m.shape.size() != first.shape.size() ||
          (m.shape.size() > 1 &&
           !std::equal(m.shape.begin() + 1, m.shape.end(),
                       first.shape.begin() + 1)))
        err = "Mismatched non-first dimensions for tensor " + name + ".";
    }
  }
  if (err.empty() && first.type == REQ_ALLTOALL) {
    size_t group = first.psr.empty() ? size_t(size) : first.psr.size();
    for (const auto& m : msgs) {
      // 0-d tensors are promoted to one row by the data plane.
      int64_t dim0 = m.shape.empty() ? 1 : m.shape[0];
      if (m.splits.size() != group) {
        err = "Alltoall splits for tensor " + name + ": rank " +
              std::to_string(m.rank) + " sent " +
              std::to_string(m.splits.size()) + " entries for a group "
              "of " + std::to_string(group) + ".";
        break;
      }
      int64_t sum = 0;
      bool neg = false;
      for (int64_t s : m.splits) { sum += s; neg = neg || s < 0; }
      if (neg) {
        err = "Alltoall splits for tensor " + name + ": rank " +
              std::to_string(m.rank) + " sent negative splits.";
        break;
      }
      if (sum != dim0) {
        // Wire parity with the Python coordinator: name the rank and
        // both sums (ragged lookup batches hit this).
        err = "Alltoall splits for tensor " + name + ": rank " +
              std::to_string(m.rank) + " splits sum to " +
              std::to_string(sum) + " but must sum to the first "
              "dimension (" + std::to_string(dim0) + ").";
        break;
      }
    }
  }
  if (!err.empty()) {
    Response r;
    r.type = RESP_ERROR;
    r.names = {name};
    r.error = err;
    r.psid = first.psid;
    return r;
  }
  Response r;
  r.type = first.type;  // enum values align 1:1
  r.names = {name};
  r.dtype = first.dtype;
  r.prescale = first.prescale;
  r.postscale = first.postscale;
  r.psid = first.psid;
  r.root = first.root;
  r.op = first.op;
  r.shapes = {first.shape};
  r.psr = first.psr;
  if (first.type == REQ_ALLGATHER) {
    // Per-rank first-dim sizes in GROUP order (process-set ranks when
    // given, else world order) — consumers slice tensor_sizes in
    // group_size strides (mirrors controller.py construct_response).
    std::map<int32_t, const Request*> by_rank;
    for (const auto& m : msgs) by_rank[m.rank] = &m;
    std::vector<int32_t> ranks;
    if (!first.psr.empty())
      ranks.assign(first.psr.begin(), first.psr.end());
    else
      for (int rk = 0; rk < size; ++rk) ranks.push_back(rk);
    for (int rk : ranks) {
      auto it = by_rank.find(rk);
      if (it != by_rank.end()) {
        const auto& sh = it->second->shape;
        r.sizes.push_back(sh.empty() ? 1 : sh[0]);
      } else {
        r.sizes.push_back(0);  // joined (departed) rank: zero rows
      }
    }
  } else if (first.type == REQ_ALLTOALL) {
    // Flattened group×group send-split matrix, rows in GROUP order —
    // rank g's recv splits are column g (mirrors controller.py;
    // reference AlltoallGetRecvSplits, mpi_controller.cc:212-223).
    std::map<int32_t, const Request*> by_rank;
    for (const auto& m : msgs) by_rank[m.rank] = &m;
    std::vector<int32_t> ranks;
    if (!first.psr.empty())
      ranks.assign(first.psr.begin(), first.psr.end());
    else
      for (int rk = 0; rk < size; ++rk) ranks.push_back(rk);
    for (int rk : ranks) {
      auto it = by_rank.find(rk);
      if (it != by_rank.end()) {
        for (int64_t s : it->second->splits) r.sizes.push_back(s);
      } else {
        for (size_t i = 0; i < ranks.size(); ++i) r.sizes.push_back(0);
      }
    }
  }
  return r;
}

// Request signature: everything that must match for a cached response
// to remain valid (mirrors response_cache.py request_signature).
struct Sig {
  std::vector<int64_t> shape;
  int32_t dtype = 7;
  int32_t root = -1;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t psid = 0;
  std::string op;
  int32_t rtype = 0;
  std::vector<int32_t> psr;
};

Sig make_sig(const Request& r) {
  Sig s;
  s.shape = r.shape;
  s.dtype = r.dtype;
  s.root = r.root;
  s.prescale = r.prescale;
  s.postscale = r.postscale;
  s.psid = r.psid;
  s.op = r.op;
  s.rtype = r.type;
  s.psr = r.psr;
  return s;
}

Request sig_to_request(const Sig& s, int rank, const std::string& name,
                       int64_t first_dim /* -1 = keep */) {
  Request r;
  r.rank = rank;
  r.type = s.rtype;
  r.name = name;
  r.shape = s.shape;
  if (first_dim >= 0 && !r.shape.empty()) r.shape[0] = first_dim;
  r.dtype = s.dtype;
  r.root = s.root;
  r.prescale = s.prescale;
  r.postscale = s.postscale;
  r.psid = s.psid;
  r.op = s.op;
  r.psr = s.psr;
  return r;
}

// Coordinator-side response cache with authoritative, monotonically
// increasing bit assignment (see response_cache.py CoordinatorCache).
// Per-tensor coordinator state (message table, caches, stall clocks)
// is keyed by process set AND name: the same tensor name may be in
// flight on two process sets at once (the reference allows this
// structurally — every process set owns its own controller,
// process_set.h ProcessSetTable).  Key format "<psid>\x1f<name>";
// \x1f cannot appear in the psid digits, so the FIRST separator
// always recovers the pure wire name even if the name itself
// contains \x1f.
inline std::string ps_key(int32_t psid, const std::string& name) {
  return std::to_string(psid) + '\x1f' + name;
}
inline std::string pure_name(const std::string& key) {
  auto pos = key.find('\x1f');
  return pos == std::string::npos ? key : key.substr(pos + 1);
}
inline int32_t key_psid(const std::string& key) {
  auto pos = key.find('\x1f');
  if (pos == std::string::npos) return 0;
  return int32_t(std::atoi(key.substr(0, pos).c_str()));
}

class CoordCache {
 public:
  struct Entry {
    int32_t bit;
    Response resp;  // per-tensor
    Sig sig;
    int32_t gid;
  };
  struct Tomb {
    std::string name;
    Sig sig;
    std::vector<int64_t> sizes;
    int32_t gid;
  };

  explicit CoordCache(int capacity) : capacity_(capacity) {}

  bool enabled() const { return capacity_ > 0; }
  bool has(const std::string& name) const { return entries_.count(name); }
  Entry* get(const std::string& name) {
    auto it = entries_.find(name);
    return it == entries_.end() ? nullptr : &it->second;
  }

  // Returns 0 = unknown, 1 = live, 2 = tombstone.
  int resolve_bit(int32_t bit, std::string* name, Sig* sig,
                  std::vector<int64_t>* sizes, int32_t* gid) {
    auto it = bit_names_.find(bit);
    if (it != bit_names_.end()) {
      Entry& e = entries_[it->second];
      // LRU: a bit contribution marks the tensor hot, so capacity
      // eviction prefers tensors no rank is actively using
      // (response_cache.py resolve_bit; reference response_cache.h
      // LRU semantics).  O(1) splice — this runs once per cached
      // tensor per step on the coordinator thread.
      touch_order(it->second);
      *name = it->second;
      *sig = e.sig;
      *sizes = e.resp.sizes;
      *gid = e.gid;
      return 1;
    }
    auto tit = tombstones_.find(bit);
    if (tit != tombstones_.end()) {
      *name = tit->second.name;
      *sig = tit->second.sig;
      *sizes = tit->second.sizes;
      *gid = tit->second.gid;
      return 2;
    }
    return 0;
  }

  int32_t insert(const std::string& name, const Response& resp,
                 const Sig& sig, int32_t gid,
                 const std::set<std::string>& pending,
                 std::vector<int32_t>* evicted) {
    auto it = entries_.find(name);
    if (it != entries_.end()) {
      tombstone(it->second.bit, name, it->second.sig,
                it->second.resp.sizes, it->second.gid);
      bit_names_.erase(it->second.bit);
      evicted->push_back(it->second.bit);
      remove_order(name);
      entries_.erase(it);
    }
    while (int(entries_.size()) >= capacity_ && capacity_ > 0) {
      std::string victim;
      for (const auto& cand : order_) {
        if (!pending.count(cand)) {
          victim = cand;
          break;
        }
      }
      if (victim.empty()) break;  // everything in flight; overgrow
      Entry& e = entries_[victim];
      tombstone(e.bit, victim, e.sig, e.resp.sizes, e.gid);
      bit_names_.erase(e.bit);
      evicted->push_back(e.bit);
      entries_.erase(victim);
      remove_order(victim);
    }
    int32_t bit = next_bit_++;
    entries_[name] = Entry{bit, resp, sig, gid};
    order_.push_back(name);
    order_it_[name] = std::prev(order_.end());
    bit_names_[bit] = name;
    return bit;
  }

  // Evict by name (full request arrived for a cached tensor); returns
  // the freed bit or -1.
  int32_t evict_name(const std::string& name) {
    auto it = entries_.find(name);
    if (it == entries_.end()) return -1;
    int32_t bit = it->second.bit;
    tombstone(bit, name, it->second.sig, it->second.resp.sizes,
              it->second.gid);
    bit_names_.erase(bit);
    entries_.erase(it);
    remove_order(name);
    return bit;
  }

  void clear_tombstones_for(const std::string& name) {
    for (auto it = tombstones_.begin(); it != tombstones_.end();) {
      if (it->second.name == name)
        it = tombstones_.erase(it);
      else
        ++it;
    }
  }

 private:
  void tombstone(int32_t bit, const std::string& name, const Sig& sig,
                 const std::vector<int64_t>& sizes, int32_t gid) {
    tombstones_[bit] = Tomb{name, sig, sizes, gid};
    tomb_order_.push_back(bit);
    while (tomb_order_.size() > 65536) {
      tombstones_.erase(tomb_order_.front());
      tomb_order_.pop_front();
    }
  }
  void remove_order(const std::string& name) {
    auto it = order_it_.find(name);
    if (it == order_it_.end()) return;
    order_.erase(it->second);
    order_it_.erase(it);
  }

  // Move to the most-recently-used end in O(1).
  void touch_order(const std::string& name) {
    auto it = order_it_.find(name);
    if (it == order_it_.end()) return;
    order_.splice(order_.end(), order_, it->second);
    it->second = std::prev(order_.end());
  }

  int capacity_;
  std::map<std::string, Entry> entries_;
  std::list<std::string> order_;  // LRU order, front = coldest
  std::map<std::string, std::list<std::string>::iterator> order_it_;
  std::map<int32_t, std::string> bit_names_;
  std::map<int32_t, Tomb> tombstones_;
  std::deque<int32_t> tomb_order_;
  int32_t next_bit_ = 0;
};

class Coordinator {
 public:
  Coordinator(int size, const std::string& bind_addr, int port,
              int64_t fusion_threshold, bool elastic,
              bool allow_ephemeral, int cache_capacity,
              double stall_warn_s, double stall_shutdown_s)
      : size_(size),
        fusion_threshold_(fusion_threshold),
        elastic_(elastic),
        cache_(cache_capacity),
        formed_(size <= 1),
        stall_warn_s_(stall_warn_s),
        stall_shutdown_s_(stall_shutdown_s) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(uint16_t(port));
    addr.sin_addr.s_addr =
        bind_addr.empty() ? INADDR_ANY : ::inet_addr(bind_addr.c_str());
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      if (!allow_ephemeral) {
        ::close(listen_fd_);
        listen_fd_ = -1;
        return;
      }
      addr.sin_port = 0;
      if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
                 sizeof(addr)) != 0) {
        ::close(listen_fd_);
        listen_fd_ = -1;
        return;
      }
    }
    ::listen(listen_fd_, size + 4);
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&bound), &blen);
    port_ = ntohs(bound.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    if (stall_warn_s_ > 0)
      stall_thread_ = std::thread([this] { StallLoop(); });
  }

  bool valid() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void set_fusion(int64_t v) { fusion_threshold_.store(v); }

  void stats(int64_t* rounds, int64_t* bytes) {
    *rounds = rounds_.load();
    *bytes = bytes_.load();
  }

  void cache_stats(int64_t* fast, int64_t* full) {
    *fast = fast_rounds_.load();
    *full = full_rounds_.load();
  }

  // Drain up to `cap` per-round fused-byte values since the last call.
  // Gives the autotuner the true per-round distribution (the GP models
  // per-round throughput; a flat average would collapse its variance).
  // Single consumer: only the host poll thread calls this. On overflow
  // the oldest rounds are dropped.
  int DrainRoundBytes(int64_t* out, int cap) {
    // Overflow clamp keeps half the ring as a safety margin: clamping
    // to exactly w - kRoundRing would put the read cursor on the slot
    // the writer fills next, and a commit racing the drain loop would
    // hand the autotuner a torn int64.  Both the clamp and the
    // published write cursor are re-evaluated EVERY iteration: a
    // single snapshot of round_w_ would let a committer lapping the
    // reader mid-loop overwrite slots the stale clamp still considered
    // safe (torn values fed to the autotuner).
    int n = 0;
    while (n < cap) {
      int64_t w = round_w_.load(std::memory_order_acquire);
      if (w - round_r_ > kRoundRing / 2) round_r_ = w - kRoundRing / 2;
      if (round_r_ >= w) break;
      out[n++] = round_bytes_[round_r_ % kRoundRing];
      ++round_r_;
    }
    return n;
  }

  // Human-readable stall attribution, one line per stalled tensor.
  std::string StallReport() {
    std::string out;
    auto now = std::chrono::steady_clock::now();
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& kv : table_) {
      if (kv.second.empty()) continue;
      auto ts = first_seen_.find(kv.first);
      if (ts == first_seen_.end()) continue;
      double age =
          std::chrono::duration<double>(now - ts->second).count();
      if (age < stall_warn_s_) continue;
      std::set<int32_t> submitted;
      for (const auto& m : kv.second) submitted.insert(m.rank);
      std::vector<int32_t> members;
      if (!kv.second[0].psr.empty())
        members = kv.second[0].psr;
      else
        for (int r = 0; r < size_; ++r) members.push_back(r);
      std::string sub, miss;
      for (int32_t r : submitted) sub += std::to_string(r) + ",";
      for (int32_t r : members)
        if (!submitted.count(r) && !joined_.count(r))
          miss += std::to_string(r) + ",";
      if (!sub.empty()) sub.pop_back();
      if (!miss.empty()) miss.pop_back();
      char line[512];
      std::snprintf(line, sizeof(line),
                    "STALL: tensor %s - ranks [%s] submitted, ranks "
                    "[%s] have not, for %.0fs\n",
                    pure_name(kv.first).c_str(), sub.c_str(),
                    miss.c_str(), age);
      out += line;
    }
    return out;
  }

  void Stop() {
    if (stop_.exchange(true)) return;  // idempotent (also ~Coordinator)
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : conns_) {
        ::shutdown(kv.second, SHUT_RDWR);
        ::close(kv.second);
      }
      conns_.clear();
    }
    stall_cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (stall_thread_.joinable()) stall_thread_.join();
    for (auto& t : rank_threads_)
      if (t.joinable()) t.join();
  }

  ~Coordinator() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      pollfd pfd{listen_fd_, POLLIN, 0};
      int rc = ::poll(&pfd, 1, 500);
      if (stop_.load()) return;
      if (rc <= 0) continue;
      int conn = ::accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) continue;
      int one = 1;
      ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // First frame: rank id.
      char magic[2];
      std::vector<uint8_t> payload;
      if (!recv_frame(conn, magic, &payload) || payload.size() < 4) {
        ::close(conn);
        continue;
      }
      int32_t rank;
      std::memcpy(&rank, payload.data(), 4);
      {
        std::lock_guard<std::mutex> g(mu_);
        conns_[rank] = conn;
        if (!formed_ && int(conns_.size()) >= size_) {
          formed_ = true;
          std::vector<PreItem> pre;
          pre.swap(pre_formed_);
          for (auto& p : pre) {
            if (p.is_hits) {
              HandleCacheHitsLocked(p.rank, p.bits);
            } else {
              std::vector<std::pair<Request, bool>> items;
              items.reserve(p.reqs.size());
              for (auto& r : p.reqs) items.emplace_back(std::move(r),
                                                        false);
              Process(p.rank, items);
            }
          }
        }
      }
      {
        std::lock_guard<std::mutex> g(departed_mu_);
        ++seen_;
      }
      rank_threads_.emplace_back(
          [this, rank, conn] { RankLoop(rank, conn); });
    }
  }

  void RankLoop(int rank, int conn) {
    bool clean = false;
    char magic[2];
    std::vector<uint8_t> payload;
    while (!stop_.load()) {
      if (!recv_frame(conn, magic, &payload)) break;
      if (magic[0] == 'C' && magic[1] == 'H') {
        std::vector<int32_t> bits;
        if (!unpack_bits(payload.data(), payload.size(), &bits)) break;
        HandleCacheHits(rank, bits);
        continue;
      }
      if (payload.size() < 5) break;
      uint8_t shutdown_flag = payload[0];
      if (shutdown_flag) {
        clean = true;
        break;
      }
      uint32_t count;
      std::memcpy(&count, payload.data() + 1, 4);
      std::vector<Request> reqs;
      size_t off = 5;
      bool ok = true;
      for (uint32_t i = 0; i < count && ok; ++i) {
        if (off + 4 > payload.size()) {
          ok = false;
          break;
        }
        uint32_t len;
        std::memcpy(&len, payload.data() + off, 4);
        off += 4;
        if (off + len > payload.size()) {
          ok = false;
          break;
        }
        Request r;
        if (!parse_request(payload.data() + off, len, &r)) {
          ok = false;
          break;
        }
        off += len;
        reqs.push_back(std::move(r));
      }
      if (!ok) break;
      HandleRequests(rank, reqs);
    }
    {
      std::lock_guard<std::mutex> g(departed_mu_);
      ++departed_;
      departed_cv_.notify_all();
    }
    if (!stop_.load()) OnRankLost(rank, clean);
  }

 public:
  void DepartureCounts(int* seen, int* departed) {
    std::lock_guard<std::mutex> g(departed_mu_);
    *seen = seen_;
    *departed = departed_;
  }

 private:

  int RequiredFor(const Request& r) const {
    return r.psr.empty() ? size_ : int(r.psr.size());
  }

  int JoinedCountFor(const Request& r) const {
    if (r.psr.empty()) return int(joined_.size());
    int c = 0;
    for (int32_t p : r.psr)
      if (joined_.count(p)) ++c;
    return c;
  }

  // Tensors waiting only on joined (departed) ranks became complete.
  // These must renegotiate in full: a cached response would carry the
  // joined rank's old contribution (e.g. nonzero allgather row
  // counts) whereas construct_response records zeros for it.
  void ScanComplete(
      std::vector<std::pair<std::string, std::vector<Request>>>* ready) {
    std::vector<std::string> done;
    for (auto& kv : table_) {
      if (kv.second.empty()) continue;
      const Request& first = kv.second[0];
      int required = RequiredFor(first);
      if (int(kv.second.size()) + JoinedCountFor(first) >= required) {
        ready->emplace_back(kv.first, kv.second);
        done.push_back(kv.first);
      }
    }
    for (const auto& n : done) {
      table_.erase(n);
      first_seen_.erase(n);
      bit_only_[n] = false;
    }
  }

  int64_t ResponseBytes(const Response& r) {
    int64_t total = 0;
    for (const auto& n : r.names) {
      auto it = elem_cache_.find(ps_key(r.psid, n));
      int64_t elems = it == elem_cache_.end() ? 0 : it->second;
      total += elems * kDtypeSize[r.dtype];
    }
    return total;
  }

  bool CanFuse(const Response& a, const Response& b) {
    if (a.type != b.type) return false;
    if (!kFusable.count(a.type)) return false;
    return a.dtype == b.dtype && a.psid == b.psid &&
           a.prescale == b.prescale && a.postscale == b.postscale &&
           a.op == b.op;
  }

  static void MergeInto(Response* base, const Response& cand) {
    base->names.insert(base->names.end(), cand.names.begin(),
                       cand.names.end());
    base->sizes.insert(base->sizes.end(), cand.sizes.begin(),
                       cand.sizes.end());
    base->shapes.insert(base->shapes.end(), cand.shapes.begin(),
                        cand.shapes.end());
  }

  // Group-atomic pre-merge: members of one grouped submission become a
  // single response BEFORE threshold-bounded fusion, so a group is
  // never split across compiled programs (fusion.py _premerge_groups;
  // reference controller.cc:199-223).
  std::vector<Response> PremergeGroups(std::vector<Response> in) {
    std::vector<Response> merged;
    std::map<std::string, size_t> index;  // group fuse-key -> position
    for (auto& resp : in) {
      int32_t gid = -1;
      if (!resp.names.empty()) {
        auto it = group_ids_.find(ps_key(resp.psid, resp.names[0]));
        if (it != group_ids_.end()) gid = it->second;
      }
      if (gid < 0 || !kFusable.count(resp.type)) {
        merged.push_back(std::move(resp));
        continue;
      }
      char key[160];
      std::snprintf(key, sizeof(key), "%d|%d|%d|%d|%.17g|%.17g|%s", gid,
                    resp.type, resp.dtype, resp.psid, resp.prescale,
                    resp.postscale, resp.op.c_str());
      auto it = index.find(key);
      if (it == index.end()) {
        index[key] = merged.size();
        merged.push_back(std::move(resp));
      } else {
        MergeInto(&merged[it->second], resp);
      }
    }
    return merged;
  }

  // Greedy fusion with look-ahead skip (fusion.py / reference
  // controller.cc:777-914).
  std::vector<Response> Fuse(std::vector<Response> queue) {
    std::vector<Response> out;
    int64_t threshold = fusion_threshold_.load();
    queue = PremergeGroups(std::move(queue));
    while (!queue.empty()) {
      Response base = std::move(queue.front());
      queue.erase(queue.begin());
      if (!kFusable.count(base.type)) {
        out.push_back(std::move(base));
        continue;
      }
      int64_t acc = ResponseBytes(base);
      size_t i = 0;
      while (i < queue.size()) {
        Response& cand = queue[i];
        if (CanFuse(base, cand)) {
          int64_t cb = ResponseBytes(cand);
          if (acc + cb <= threshold) {
            MergeInto(&base, cand);
            acc += cb;
            queue.erase(queue.begin() + i);
            continue;
          }
          break;  // full; keep remaining order intact
        }
        ++i;  // look-ahead skip
      }
      out.push_back(std::move(base));
    }
    return out;
  }

  void BroadcastLocked(const std::vector<Response>& responses) {
    BroadcastFrameLocked("RS", pack_response_list(responses));
  }

  void BroadcastFrameLocked(const char magic[2],
                            const std::vector<uint8_t>& payload) {
    std::vector<int> dead;
    for (auto& kv : conns_) {
      if (!send_frame(kv.second, magic, payload))
        dead.push_back(kv.first);
    }
    for (int r : dead) {
      ::close(conns_[r]);
      conns_.erase(r);
    }
  }

  void FlushEvictionsLocked() {
    if (pending_evictions_.empty()) return;
    BroadcastFrameLocked("EV", pack_bits(pending_evictions_));
    pending_evictions_.clear();
  }

  void HandleRequests(int rank, const std::vector<Request>& reqs) {
    std::lock_guard<std::mutex> g(mu_);
    if (!formed_ && !broken_) {
      // Formation gate: a response completed among early connectors
      // would never reach a not-yet-connected rank (broadcast goes to
      // conns_ only) — buffer until every rank registered (drained in
      // arrival order by AcceptLoop; mirrors controller_net.py).
      PreItem p;
      p.rank = rank;
      p.reqs = reqs;
      pre_formed_.push_back(std::move(p));
      return;
    }
    std::vector<std::pair<Request, bool>> items;
    items.reserve(reqs.size());
    for (const auto& r : reqs) items.emplace_back(r, false);
    Process(rank, items);
  }

  void HandleCacheHits(int rank, const std::vector<int32_t>& bits) {
    std::lock_guard<std::mutex> g(mu_);
    if (!formed_ && !broken_) {  // defense; no bit precedes 1st RS
      PreItem p;
      p.rank = rank;
      p.is_hits = true;
      p.bits = bits;
      pre_formed_.push_back(std::move(p));
      return;
    }
    HandleCacheHitsLocked(rank, bits);
  }

  void HandleCacheHitsLocked(int rank, const std::vector<int32_t>& bits) {
    std::vector<std::pair<Request, bool>> items;
    for (int32_t bit : bits) {
      std::string name;
      Sig sig;
      std::vector<int64_t> sizes;
      int32_t gid;
      int state = cache_.resolve_bit(bit, &name, &sig, &sizes, &gid);
      name = pure_name(name);  // cache keys are ps_key(psid, name)
      if (state == 0) {
        std::fprintf(stderr,
                     "[hvd-coord] unresolvable cache bit %d from rank "
                     "%d; protocol desync\n",
                     bit, rank);
        Response r;
        r.type = RESP_ERROR;
        r.names = {"__cache_bit_" + std::to_string(bit)};
        r.error = "response-cache protocol desync";
        BroadcastLocked({r});
        continue;
      }
      int64_t first_dim = -1;
      if (sig.rtype == REQ_ALLGATHER && !sizes.empty()) {
        // tensor_sizes are in GROUP order: index by the rank's
        // position in the process set when one is given.
        int idx = rank;
        if (!sig.psr.empty()) {
          idx = -1;
          for (size_t gi = 0; gi < sig.psr.size(); ++gi)
            if (sig.psr[gi] == rank) { idx = int(gi); break; }
        }
        if (idx >= 0 && idx < int(sizes.size())) first_dim = sizes[idx];
      }
      Request req = sig_to_request(sig, rank, name, first_dim);
      req.group_id = gid;
      // A tombstoned bit still counts, but forces the full path.
      items.emplace_back(std::move(req), state == 1);
    }
    if (!items.empty()) Process(rank, items);
  }

  void Process(int rank, const std::vector<std::pair<Request, bool>>& items) {
    if (broken_) {
      std::vector<Response> errs;
      for (const auto& it : items) {
        Response r;
        r.type = RESP_ERROR;
        r.names = {it.first.name};
        r.psid = it.first.psid;
        r.error = "membership changed; collective cannot complete";
        errs.push_back(std::move(r));
      }
      if (!errs.empty()) BroadcastLocked(errs);
      return;
    }
    // Completed negotiations and direct (join/barrier) responses, in
    // one ordered list so the broadcast interleaves them exactly as
    // they completed (matching controller_net.py's ready list).
    struct ReadyItem {
      std::string name;           // pure wire name
      std::string key;            // ps_key(psid, name)
      std::vector<Request> msgs;  // empty for direct responses
      bool is_direct = false;
      Response direct;
    };
    std::vector<ReadyItem> ready;
    for (const auto& item : items) {
      const Request& req = item.first;
      bool from_cache = item.second;
      const std::string key = ps_key(req.psid, req.name);
      int64_t n = 1;
      for (int64_t d : req.shape) n *= d;
      elem_cache_[key] = n;
      group_ids_[key] = req.group_id;
      if (req.type == REQ_JOIN) {
        joined_.insert(rank);
        last_joined_ = rank;
        if (int(joined_.size()) == size_) {
          ReadyItem ri;
          ri.is_direct = true;
          ri.direct.type = RESP_JOIN;
          ri.direct.names = {"join"};
          ri.direct.last_joined = last_joined_;
          ready.push_back(std::move(ri));
          joined_.clear();
        } else {
          std::vector<std::pair<std::string, std::vector<Request>>>
              scanned;
          ScanComplete(&scanned);
          for (auto& kv : scanned) {
            ReadyItem ri;
            ri.key = std::move(kv.first);
            ri.name = kv.second[0].name;
            ri.msgs = std::move(kv.second);
            ready.push_back(std::move(ri));
          }
        }
        continue;
      }
      if (req.type == REQ_BARRIER) {
        int required = RequiredFor(req);
        auto& arrived = barriers_[key];
        arrived.insert(rank);
        if (int(arrived.size()) >= required) {
          barriers_.erase(key);
          ReadyItem ri;
          ri.is_direct = true;
          ri.direct.type = RESP_BARRIER;
          ri.direct.names = {req.name};
          ri.direct.psid = req.psid;
          ri.direct.psr = req.psr;
          ready.push_back(std::move(ri));
        }
        continue;
      }
      if (!from_cache) {
        bit_only_[key] = false;
        if (cache_.has(key)) {
          // Signature changed on some rank (or worker-side
          // invalidation): renegotiate so a stale response can never
          // serve.
          int32_t bit = cache_.evict_name(key);
          if (bit >= 0) pending_evictions_.push_back(bit);
        }
      } else if (!bit_only_.count(key)) {
        bit_only_[key] = true;
      }
      int required = RequiredFor(req);
      if (!first_seen_.count(key))
        first_seen_[key] = std::chrono::steady_clock::now();
      auto& msgs = table_[key];
      msgs.push_back(req);
      if (int(msgs.size()) + JoinedCountFor(req) >= required) {
        ReadyItem ri;
        ri.name = req.name;
        ri.key = key;
        ri.msgs = std::move(msgs);
        table_.erase(key);
        first_seen_.erase(key);
        ready.push_back(std::move(ri));
      }
    }
    if (ready.empty()) {
      FlushEvictionsLocked();
      return;
    }

    // Group atomicity: a grouped submission must not straddle the CB
    // and RS frames — if any member renegotiates this round, every
    // member of that group is demoted to the full path
    // (controller_net.py full_groups).
    std::set<int32_t> full_gids;
    for (const auto& ri : ready) {
      if (ri.is_direct) continue;
      auto bo = bit_only_.find(ri.key);
      bool bit_only = bo != bit_only_.end() && bo->second;
      if (!(bit_only && cache_.get(ri.key) != nullptr)) {
        auto git = group_ids_.find(ri.key);
        if (git != group_ids_.end() && git->second >= 0)
          full_gids.insert(git->second);
      }
    }

    // Partition: pure-bit rounds ride the compact CB frame.
    std::vector<Response> hit_responses;
    std::vector<Response> full_responses;
    std::map<std::string, Sig> sig_by_name;
    for (auto& ri : ready) {
      if (ri.is_direct) {
        full_responses.push_back(std::move(ri.direct));
        continue;
      }
      const std::string& name = ri.name;
      const std::string& key = ri.key;
      bool bit_only = false;
      auto bo = bit_only_.find(key);
      if (bo != bit_only_.end()) {
        bit_only = bo->second;
        bit_only_.erase(bo);
      }
      CoordCache::Entry* ent = cache_.get(key);
      int32_t gid = -1;
      auto git = group_ids_.find(key);
      if (git != group_ids_.end()) gid = git->second;
      // While any rank is joined, cached responses are stale for it
      // (renegotiation substitutes zeros for joined ranks) — bypass
      // the fast path entirely.
      if (bit_only && ent != nullptr && joined_.empty() &&
          (gid < 0 || !full_gids.count(gid))) {
        hit_responses.push_back(ent->resp);
        continue;
      }
      Response resp = construct_response(name, ri.msgs, size_);
      sig_by_name[key] = make_sig(ri.msgs[0]);
      full_responses.push_back(std::move(resp));
      cache_.clear_tombstones_for(key);
    }

    int64_t nbytes = 0;
    if (!hit_responses.empty()) {
      auto fused_hits = Fuse(hit_responses);
      std::vector<std::vector<int32_t>> batches;
      for (const auto& fr : fused_hits) {
        std::vector<int32_t> batch;
        for (const auto& n : fr.names) {
          CoordCache::Entry* e = cache_.get(ps_key(fr.psid, n));
          batch.push_back(e ? e->bit : -1);
        }
        batches.push_back(std::move(batch));
        nbytes += ResponseBytes(fr);
      }
      BroadcastFrameLocked("CB", pack_bit_batches(batches));
      fast_rounds_.fetch_add(1);
    }
    if (!full_responses.empty()) {
      auto fused = Fuse(std::move(full_responses));
      if (cache_.enabled()) AssignCacheBits(&fused, sig_by_name);
      FlushEvictionsLocked();
      BroadcastLocked(fused);
      full_rounds_.fetch_add(1);
      for (const auto& r : fused) nbytes += ResponseBytes(r);
    } else {
      FlushEvictionsLocked();
    }
    rounds_.fetch_add(1);
    bytes_.fetch_add(nbytes);
    // Per-round history for the autotuner (written under mu_; the
    // host poll thread is the single reader).
    round_bytes_[round_w_.load(std::memory_order_relaxed) % kRoundRing] =
        nbytes;
    round_w_.fetch_add(1, std::memory_order_release);
  }

  // Slice a fused response into per-tensor responses (mirrors
  // response_cache.py split_response) and seed the cache, stamping the
  // assigned bits onto the wire.
  void AssignCacheBits(std::vector<Response>* fused,
                       const std::map<std::string, Sig>& sig_by_name) {
    std::set<std::string> pending;
    for (const auto& kv : table_) pending.insert(kv.first);
    for (auto& resp : *fused) {
      if (!kCacheable.count(resp.type) || !resp.error.empty()) continue;
      size_t group = resp.psr.empty() ? size_t(size_) : resp.psr.size();
      size_t per_sizes = 0;
      if (resp.type == RESP_ALLGATHER && group > 0 &&
          resp.sizes.size() == group * resp.names.size())
        per_sizes = group;
      resp.cache_bits.clear();
      for (size_t i = 0; i < resp.names.size(); ++i) {
        const std::string key = ps_key(resp.psid, resp.names[i]);
        auto sit = sig_by_name.find(key);
        if (sit == sig_by_name.end()) {
          resp.cache_bits.push_back(-1);
          continue;
        }
        Response part;
        part.type = resp.type;
        part.dtype = resp.dtype;
        part.prescale = resp.prescale;
        part.postscale = resp.postscale;
        part.psid = resp.psid;
        part.root = resp.root;
        part.op = resp.op;
        part.names = {resp.names[i]};
        if (per_sizes)
          part.sizes.assign(resp.sizes.begin() + i * per_sizes,
                            resp.sizes.begin() + (i + 1) * per_sizes);
        else
          part.sizes = resp.sizes;
        if (i < resp.shapes.size()) part.shapes = {resp.shapes[i]};
        part.psr = resp.psr;
        auto git = group_ids_.find(key);
        int32_t gid = git == group_ids_.end() ? -1 : git->second;
        int32_t bit = cache_.insert(key, part, sit->second,
                                    gid, pending, &pending_evictions_);
        resp.cache_bits.push_back(bit);
      }
    }
  }

  // Pre-formation requests never enter table_, so StallReport is
  // blind to a rank that dies before connecting — attribute that
  // stall here and, past the shutdown threshold, fail the buffered
  // collectives (mirrors controller_net.py _check_formation_stall).
  void CheckFormationStall() {
    std::lock_guard<std::mutex> g(mu_);
    if (formed_ || pre_formed_.empty()) return;
    double age = std::chrono::duration<double>(
        std::chrono::steady_clock::now() - started_at_).count();
    if (age < stall_warn_s_) return;
    std::string miss;
    for (int r = 0; r < size_; ++r)
      if (!conns_.count(r)) miss += std::to_string(r) + ",";
    if (!miss.empty()) miss.pop_back();
    std::fprintf(stderr,
                 "STALL: waiting for ranks [%s] to connect for %.0fs "
                 "(%zu/%d registered, %zu requests buffered)\n",
                 miss.c_str(), age, conns_.size(), size_,
                 pre_formed_.size());
    if (stall_shutdown_s_ > 0 && age >= stall_shutdown_s_) {
      std::vector<PreItem> pre;
      pre.swap(pre_formed_);
      std::vector<Response> errs;
      for (auto& p : pre) {
        for (auto& rq : p.reqs) {
          Response r;
          r.type = RESP_ERROR;
          r.names = {rq.name};
          r.psid = rq.psid;
          r.error = "ranks [" + miss + "] never connected within " +
                    std::to_string(int(stall_shutdown_s_)) + "s";
          errs.push_back(std::move(r));
        }
      }
      if (!errs.empty()) BroadcastLocked(errs);
    }
  }

  void StallLoop() {
    double interval = stall_warn_s_ / 2.0;
    if (interval > 10.0) interval = 10.0;
    if (interval < 0.25) interval = 0.25;
    std::unique_lock<std::mutex> lk(stall_mu_);
    while (!stop_.load()) {
      stall_cv_.wait_for(lk, std::chrono::duration<double>(interval));
      if (stop_.load()) return;
      CheckFormationStall();
      auto report = StallReport();
      if (!report.empty()) std::fprintf(stderr, "%s", report.c_str());
      if (stall_shutdown_s_ <= 0) continue;
      // Fail collectives stalled past the shutdown threshold.
      auto now = std::chrono::steady_clock::now();
      std::lock_guard<std::mutex> g(mu_);
      std::vector<std::string> doomed;
      for (const auto& kv : table_) {
        auto ts = first_seen_.find(kv.first);
        if (ts == first_seen_.end()) continue;
        double age =
            std::chrono::duration<double>(now - ts->second).count();
        if (age >= stall_shutdown_s_) doomed.push_back(kv.first);
      }
      for (const auto& key : doomed) {
        table_.erase(key);
        first_seen_.erase(key);
        bit_only_.erase(key);
        Response r;
        r.type = RESP_ERROR;
        r.names = {pure_name(key)};
        r.psid = key_psid(key);  // workers pop entries by (name, psid)
        r.error = "collective " + pure_name(key) +
                  " stalled past the shutdown threshold";
        BroadcastLocked({r});
      }
    }
  }

  void OnRankLost(int rank, bool clean) {
    if (!elastic_) return;
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(rank);
    if (it != conns_.end()) {
      ::close(it->second);
      conns_.erase(it);
    }
    broken_ = true;
    std::vector<Response> errs;
    std::string msg = "rank " + std::to_string(rank) +
                      " left the job (" +
                      (clean ? "clean" : "connection lost") +
                      "); membership changed";
    for (auto& kv : table_) {
      Response r;
      r.type = RESP_ERROR;
      r.names = {pure_name(kv.first)};
      r.psid = key_psid(kv.first);
      r.error = msg;
      errs.push_back(std::move(r));
    }
    for (auto& kv : barriers_) {
      Response r;
      r.type = RESP_ERROR;
      r.names = {pure_name(kv.first)};
      r.psid = key_psid(kv.first);
      r.error = msg;
      errs.push_back(std::move(r));
    }
    for (auto& p : pre_formed_) {  // pre-formation buffered submitters
      for (auto& rq : p.reqs) {
        Response r;
        r.type = RESP_ERROR;
        r.names = {rq.name};
        r.psid = rq.psid;
        r.error = msg;
        errs.push_back(std::move(r));
      }
    }
    pre_formed_.clear();
    table_.clear();
    barriers_.clear();
    first_seen_.clear();
    bit_only_.clear();
    if (!errs.empty()) BroadcastLocked(errs);
    // Abort broadcast: workers with no pending eager negotiation
    // (blocked in framework-plane collectives or compute) must learn
    // the membership broke while this coordinator is still up, so
    // they can disconnect their jax client before rank 0 takes the
    // coordination service down (leader loss under an attached
    // client is process-fatal).  Mirrors the Python coordinator.
    BroadcastFrameLocked("AB",
                         std::vector<uint8_t>(msg.begin(), msg.end()));
  }

  int size_;
  std::atomic<int64_t> fusion_threshold_;
  bool elastic_;
  CoordCache cache_;
  double stall_warn_s_;
  double stall_shutdown_s_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stop_{false};
  std::thread accept_thread_;
  std::thread stall_thread_;
  std::vector<std::thread> rank_threads_;

  std::mutex mu_;
  // Formation gate: uplink frames buffered until every rank connects
  // (see HandleRequests).
  struct PreItem {
    int rank = -1;
    bool is_hits = false;
    std::vector<Request> reqs;
    std::vector<int32_t> bits;
  };
  bool formed_ = false;
  std::vector<PreItem> pre_formed_;
  std::chrono::steady_clock::time_point started_at_ =
      std::chrono::steady_clock::now();
  std::map<int, int> conns_;                      // rank -> fd
  std::map<std::string, std::vector<Request>> table_;
  std::map<std::string, std::set<int>> barriers_;
  std::map<std::string, int64_t> elem_cache_;
  std::map<std::string, int32_t> group_ids_;
  std::map<std::string, bool> bit_only_;
  std::map<std::string, std::chrono::steady_clock::time_point> first_seen_;
  std::vector<int32_t> pending_evictions_;
  std::set<int> joined_;
  int last_joined_ = -1;
  bool broken_ = false;
  std::mutex departed_mu_;
  std::condition_variable departed_cv_;
  std::mutex stall_mu_;
  std::condition_variable stall_cv_;
  int seen_ = 0;
  int departed_ = 0;
  std::atomic<int64_t> rounds_{0};
  std::atomic<int64_t> bytes_{0};
  std::atomic<int64_t> fast_rounds_{0};
  std::atomic<int64_t> full_rounds_{0};
  static constexpr int kRoundRing = 8192;
  std::vector<int64_t> round_bytes_ = std::vector<int64_t>(kRoundRing);
  std::atomic<int64_t> round_w_{0};
  int64_t round_r_ = 0;  // poll-thread-owned cursor
};

}  // namespace

extern "C" {

void* hvd_coord_create(int size, const char* bind_addr, int port,
                       long long fusion_threshold, int elastic,
                       int allow_ephemeral, int cache_capacity,
                       double stall_warn_s, double stall_shutdown_s) {
  auto* c = new Coordinator(size, bind_addr ? bind_addr : "", port,
                            fusion_threshold, elastic != 0,
                            allow_ephemeral != 0, cache_capacity,
                            stall_warn_s, stall_shutdown_s);
  if (!c->valid()) {
    delete c;
    return nullptr;
  }
  return c;
}

int hvd_coord_port(void* h) {
  return static_cast<Coordinator*>(h)->port();
}

void hvd_coord_set_fusion(void* h, long long v) {
  static_cast<Coordinator*>(h)->set_fusion(v);
}

void hvd_coord_stats(void* h, long long* rounds, long long* bytes) {
  int64_t r, b;
  static_cast<Coordinator*>(h)->stats(&r, &b);
  *rounds = r;
  *bytes = b;
}

void hvd_coord_cache_stats(void* h, long long* fast_rounds,
                           long long* full_rounds) {
  int64_t f, n;
  static_cast<Coordinator*>(h)->cache_stats(&f, &n);
  *fast_rounds = f;
  *full_rounds = n;
}

int hvd_coord_drain_round_bytes(void* h, long long* out, int cap) {
  static_assert(sizeof(long long) == sizeof(int64_t), "ABI");
  return static_cast<Coordinator*>(h)->DrainRoundBytes(
      reinterpret_cast<int64_t*>(out), cap);
}

int hvd_coord_stall_report(void* h, char* buf, int cap) {
  std::string s = static_cast<Coordinator*>(h)->StallReport();
  int n = int(s.size());
  if (n > cap - 1) n = cap - 1;
  if (n < 0) n = 0;
  std::memcpy(buf, s.data(), size_t(n));
  buf[n] = '\0';
  return n;
}

void hvd_coord_counts(void* h, int* seen, int* departed) {
  static_cast<Coordinator*>(h)->DepartureCounts(seen, departed);
}

void hvd_coord_stop(void* h) {
  auto* c = static_cast<Coordinator*>(h);
  c->Stop();
  delete c;
}

}  // extern "C"
