// Native CPU collectives: TCP full-mesh ring allreduce/allgather/
// broadcast/barrier.
//
// The C++ equivalent of the reference's CPU collective backend
// (reference: ops/gloo_operations.{h,cc} — gloo ring algorithms over a
// full-mesh TCP rendezvous, gloo/gloo_context.cc:63-216).  On TPU the
// data plane is compiled XLA collectives over ICI; this backend serves
// the same role the reference's Gloo ops do — CPU rigs and host-side
// tensors — where per-call dispatch of a multi-controller XLA program
// costs milliseconds while a direct ring over persistent sockets costs
// microseconds.
//
// Build: compiled together with coordinator.cc into libhvdtpu_coord.so
// (see native/__init__.py).
//
// C API (ctypes):
//   void* hvd_ring_create(int rank, int size);
//   int   hvd_ring_listen(void*);                     // returns port
//   int   hvd_ring_connect(void*, const char* addrs_csv); // 0 = ok
//   int   hvd_ring_allreduce(void*, void* buf, long long n,
//                            int dtype, int op,
//                            const int* ranks, int nranks);
//   int   hvd_ring_allgather(void*, const void* inbuf, long long inbytes,
//                            void* outbuf, const long long* counts,
//                            const int* ranks, int nranks);
//   int   hvd_ring_broadcast(void*, void* buf, long long nbytes,
//                            int root, const int* ranks, int nranks);
//   int   hvd_ring_alltoall(void*, const void* inbuf, void* outbuf,
//                           const long long* sendcounts_bytes,
//                           const long long* recvcounts_bytes,
//                           const int* ranks, int nranks);
//   int   hvd_ring_reducescatter(void*, void* buf,
//                                const long long* counts /*elements*/,
//                                int dtype, int op, void* outbuf,
//                                const int* ranks, int nranks);
//   int   hvd_ring_barrier(void*, const int* ranks, int nranks);
//   void  hvd_ring_destroy(void*);
//
// dtype codes: 0=f32 1=f64 2=i32 3=i64; op codes: 0=sum 1=prod 2=min
// 3=max.  ranks/nranks select a process-set subgroup (NULL/0 = world).
// All calls are made from the single background runtime thread; no
// internal locking is needed beyond construction.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

namespace {

// Large socket buffers keep the duplex ring streaming instead of
// thrashing 64 KB at a time through poll+send+recv syscalls.
void tune_socket(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int buf = 8 * 1024 * 1024;
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

struct RingComm {
  int rank = -1;
  int size = 0;
  int listen_fd = -1;
  std::vector<int> fds;  // peer rank -> connected fd (-1 for self)
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Full-duplex exchange: drive send and recv together with poll() and
// NON-BLOCKING partial I/O, so large simultaneous transfers cannot
// deadlock on full TCP buffers — a blocking send() on Linux copies the
// whole request and would park both ring neighbors in send() while
// neither drains its receive side (the reference's gloo pairs run the
// same duplex state machine internally).
bool send_recv(int send_fd, const void* sbuf, size_t sn,
               int recv_fd, void* rbuf, size_t rn) {
  // Large transfers: a dedicated sender thread + inline blocking recv
  // saturates both directions of the pipe; the poll loop below
  // time-slices one core and tops out at about half the link rate.
  if (sn + rn >= (4u << 20)) {
    bool send_ok = true;
    std::thread sender(
        [&] { send_ok = send_all(send_fd, sbuf, sn); });
    bool recv_ok = recv_all(recv_fd, rbuf, rn);
    sender.join();
    return send_ok && recv_ok;
  }
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  while (sn > 0 || rn > 0) {
    struct pollfd pfds[2];
    int npfd = 0;
    int si = -1, ri = -1;
    if (sn > 0) {
      pfds[npfd] = {send_fd, POLLOUT, 0};
      si = npfd++;
    }
    if (rn > 0) {
      pfds[npfd] = {recv_fd, POLLIN, 0};
      ri = npfd++;
    }
    if (::poll(pfds, npfd, 30000) <= 0) return false;
    if (si >= 0 && (pfds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t k = ::send(send_fd, sp, sn, MSG_NOSIGNAL | MSG_DONTWAIT);
      if (k <= 0 && !(k < 0 && (errno == EINTR || errno == EAGAIN ||
                                errno == EWOULDBLOCK)))
        return false;
      if (k > 0) { sp += k; sn -= static_cast<size_t>(k); }
    }
    if (ri >= 0 && (pfds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = ::recv(recv_fd, rp, rn, MSG_DONTWAIT);
      if (k <= 0 && !(k < 0 && (errno == EINTR || errno == EAGAIN ||
                                errno == EWOULDBLOCK)))
        return false;
      if (k > 0) { rp += k; rn -= static_cast<size_t>(k); }
    }
  }
  return true;
}

size_t dtype_size(int dtype) {
  switch (dtype) {
    case 0: return 4;  // f32
    case 1: return 8;  // f64
    case 2: return 4;  // i32
    case 3: return 8;  // i64
  }
  return 0;
}

template <typename T>
void reduce_typed(T* dst, const T* src, int64_t n, int op) {
  switch (op) {
    case 0: for (int64_t i = 0; i < n; ++i) dst[i] += src[i]; break;
    case 1: for (int64_t i = 0; i < n; ++i) dst[i] *= src[i]; break;
    case 2: for (int64_t i = 0; i < n; ++i)
              dst[i] = std::min(dst[i], src[i]);
            break;
    case 3: for (int64_t i = 0; i < n; ++i)
              dst[i] = std::max(dst[i], src[i]);
            break;
  }
}

void reduce_buf(void* dst, const void* src, int64_t n, int dtype, int op) {
  switch (dtype) {
    case 0: reduce_typed(static_cast<float*>(dst),
                         static_cast<const float*>(src), n, op); break;
    case 1: reduce_typed(static_cast<double*>(dst),
                         static_cast<const double*>(src), n, op); break;
    case 2: reduce_typed(static_cast<int32_t*>(dst),
                         static_cast<const int32_t*>(src), n, op); break;
    case 3: reduce_typed(static_cast<int64_t*>(dst),
                         static_cast<const int64_t*>(src), n, op); break;
  }
}

// Resolve the subgroup: world when ranks==NULL. Returns my index in
// the group, or -1 when not a member.
int group_index(const RingComm* c, const int* ranks, int nranks,
                std::vector<int>* group) {
  if (ranks == nullptr || nranks <= 0) {
    group->resize(c->size);
    for (int i = 0; i < c->size; ++i) (*group)[i] = i;
    return c->rank;
  }
  group->assign(ranks, ranks + nranks);
  for (int i = 0; i < nranks; ++i)
    if ((*group)[i] == c->rank) return i;
  return -1;
}

}  // namespace

extern "C" {

void* hvd_ring_create(int rank, int size) {
  auto* c = new RingComm;
  c->rank = rank;
  c->size = size;
  c->fds.assign(size, -1);
  return c;
}

int hvd_ring_listen(void* h) {
  auto* c = static_cast<RingComm*>(h);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, c->size) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  c->listen_fd = fd;
  return ntohs(addr.sin_port);
}

// addrs_csv: "ip:port,ip:port,..." indexed by rank. Full mesh: rank i
// connects to every j < i and accepts from every j > i (the same mesh
// shape gloo's rendezvous builds, gloo/gloo_context.cc:63-84).
int hvd_ring_connect(void* h, const char* addrs_csv) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<std::string> addrs;
  std::string s(addrs_csv), cur;
  for (char ch : s) {
    if (ch == ',') { addrs.push_back(cur); cur.clear(); }
    else cur.push_back(ch);
  }
  if (!cur.empty()) addrs.push_back(cur);
  if (static_cast<int>(addrs.size()) != c->size) return -1;

  for (int j = 0; j < c->rank; ++j) {
    auto pos = addrs[j].rfind(':');
    if (pos == std::string::npos) return -1;
    std::string host = addrs[j].substr(0, pos);
    int port = std::stoi(addrs[j].substr(pos + 1));
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in peer{};
    peer.sin_family = AF_INET;
    peer.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &peer.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    // Retry briefly: peers bring their listeners up concurrently.
    int rc = -1;
    for (int attempt = 0; attempt < 600; ++attempt) {
      rc = ::connect(fd, reinterpret_cast<sockaddr*>(&peer),
                     sizeof(peer));
      if (rc == 0) break;
      ::close(fd);
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      struct timespec ts = {0, 50 * 1000 * 1000};
      ::nanosleep(&ts, nullptr);
    }
    if (rc != 0) { ::close(fd); return -1; }
    tune_socket(fd);
    int32_t my_rank = c->rank;
    if (!send_all(fd, &my_rank, 4)) { ::close(fd); return -1; }
    c->fds[j] = fd;
  }
  for (int j = c->rank + 1; j < c->size; ++j) {
    // Bounded accept: a peer that died before connecting must surface
    // as an error here, not an infinite hang in init.
    struct pollfd pfd = {c->listen_fd, POLLIN, 0};
    if (::poll(&pfd, 1, 60000) <= 0) return -6;
    int fd = ::accept(c->listen_fd, nullptr, nullptr);
    if (fd < 0) return -1;
    tune_socket(fd);
    int32_t peer_rank = -1;
    if (!recv_all(fd, &peer_rank, 4) || peer_rank < 0 ||
        peer_rank >= c->size) {
      ::close(fd);
      return -1;
    }
    c->fds[peer_rank] = fd;
  }
  return 0;
}

// In-place ring allreduce: reduce-scatter then allgather
// (reference: gloo's ring algorithm, ops/gloo_operations.cc:32-75).
int hvd_ring_allreduce(void* h, void* buf, long long n, int dtype,
                       int op, const int* ranks, int nranks) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<int> group;
  int me = group_index(c, ranks, nranks, &group);
  if (me < 0) return -1;
  int p = static_cast<int>(group.size());
  if (p == 1) return 0;
  size_t es = dtype_size(dtype);
  if (es == 0) return -2;

  int right = c->fds[group[(me + 1) % p]];
  int left = c->fds[group[(me - 1 + p) % p]];
  if (right < 0 || left < 0) return -3;

  // Chunk boundaries: chunk i owns [off[i], off[i+1]).
  std::vector<int64_t> off(p + 1);
  for (int i = 0; i <= p; ++i) off[i] = n * i / p;
  char* base = static_cast<char*>(buf);
  int64_t max_chunk = 0;
  for (int i = 0; i < p; ++i)
    max_chunk = std::max(max_chunk, off[i + 1] - off[i]);
  std::vector<char> tmp(static_cast<size_t>(max_chunk) * es);

  // Reduce-scatter: after p-1 steps, chunk (me+1)%p holds the full
  // reduction on this rank.
  for (int s = 0; s < p - 1; ++s) {
    int send_c = ((me - s) % p + p) % p;
    int recv_c = ((me - s - 1) % p + p) % p;
    int64_t sn = off[send_c + 1] - off[send_c];
    int64_t rn = off[recv_c + 1] - off[recv_c];
    if (!send_recv(right, base + off[send_c] * es,
                   static_cast<size_t>(sn) * es, left, tmp.data(),
                   static_cast<size_t>(rn) * es))
      return -4;
    reduce_buf(base + off[recv_c] * es, tmp.data(), rn, dtype, op);
  }
  // Allgather: circulate the finished chunks.
  for (int s = 0; s < p - 1; ++s) {
    int send_c = ((me + 1 - s) % p + p) % p;
    int recv_c = ((me - s) % p + p) % p;
    int64_t sn = off[send_c + 1] - off[send_c];
    int64_t rn = off[recv_c + 1] - off[recv_c];
    if (!send_recv(right, base + off[send_c] * es,
                   static_cast<size_t>(sn) * es, left,
                   base + off[recv_c] * es,
                   static_cast<size_t>(rn) * es))
      return -4;
  }
  return 0;
}

// Ring allgather with per-rank byte counts; outbuf is the
// concatenation in group order (counts[i] bytes from group rank i).
int hvd_ring_allgather(void* h, const void* inbuf, long long inbytes,
                       void* outbuf, const long long* counts,
                       const int* ranks, int nranks) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<int> group;
  int me = group_index(c, ranks, nranks, &group);
  if (me < 0) return -1;
  int p = static_cast<int>(group.size());
  std::vector<int64_t> off(p + 1, 0);
  for (int i = 0; i < p; ++i) off[i + 1] = off[i] + counts[i];
  char* out = static_cast<char*>(outbuf);
  std::memcpy(out + off[me], inbuf, static_cast<size_t>(inbytes));
  if (p == 1) return 0;
  int right = c->fds[group[(me + 1) % p]];
  int left = c->fds[group[(me - 1 + p) % p]];
  if (right < 0 || left < 0) return -3;
  for (int s = 0; s < p - 1; ++s) {
    int send_c = ((me - s) % p + p) % p;
    int recv_c = ((me - s - 1) % p + p) % p;
    if (!send_recv(right, out + off[send_c],
                   static_cast<size_t>(counts[send_c]), left,
                   out + off[recv_c],
                   static_cast<size_t>(counts[recv_c])))
      return -4;
  }
  return 0;
}

// Binomial-tree broadcast within the group (root = group index).
int hvd_ring_broadcast(void* h, void* buf, long long nbytes, int root,
                       const int* ranks, int nranks) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<int> group;
  int me = group_index(c, ranks, nranks, &group);
  if (me < 0) return -1;
  int p = static_cast<int>(group.size());
  if (p == 1) return 0;
  if (root < 0 || root >= p) return -2;
  // Rotate so the root is virtual rank 0; at each doubling step the
  // first `dist` virtual ranks (which hold the data) seed the next
  // `dist`.
  int vme = (me - root + p) % p;
  for (int dist = 1; dist < p; dist <<= 1) {
    if (vme < dist && vme + dist < p) {
      int peer = group[((vme + dist) + root) % p];
      if (!send_all(c->fds[peer], buf, static_cast<size_t>(nbytes)))
        return -4;
    } else if (vme >= dist && vme < (dist << 1)) {
      int peer = group[((vme - dist) + root) % p];
      if (!recv_all(c->fds[peer], buf, static_cast<size_t>(nbytes)))
        return -4;
    }
  }
  return 0;
}

// Pairwise-exchange alltoall with uneven byte counts — the semantics
// of MPI_Alltoallv (reference: operations.cc:1099-1160 alltoall with
// splits, ops/mpi_operations.cc MPIAlltoall). sendcounts[i] bytes from
// inbuf go to group rank i; recvcounts[i] bytes from group rank i land
// in outbuf; both buffers are packed in group order. Pure data
// movement: dtype-agnostic.
//
// Schedule: at step s, send to (me+s)%p while receiving from (me-s)%p.
// Each ordered pair (a -> b) is touched in exactly one step
// (s = b-a mod p), so per-socket streams never interleave even though
// ranks drift across steps.
int hvd_ring_alltoall(void* h, const void* inbuf, void* outbuf,
                      const long long* sendcounts,
                      const long long* recvcounts,
                      const int* ranks, int nranks) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<int> group;
  int me = group_index(c, ranks, nranks, &group);
  if (me < 0) return -1;
  int p = static_cast<int>(group.size());
  std::vector<int64_t> soff(p + 1, 0), roff(p + 1, 0);
  for (int i = 0; i < p; ++i) {
    soff[i + 1] = soff[i] + sendcounts[i];
    roff[i + 1] = roff[i] + recvcounts[i];
  }
  const char* in = static_cast<const char*>(inbuf);
  char* out = static_cast<char*>(outbuf);
  if (sendcounts[me] > 0)
    std::memcpy(out + roff[me], in + soff[me],
                static_cast<size_t>(sendcounts[me]));
  for (int s = 1; s < p; ++s) {
    int to = (me + s) % p;
    int from = (me - s + p) % p;
    int sfd = c->fds[group[to]];
    int rfd = c->fds[group[from]];
    if (sfd < 0 || rfd < 0) return -3;
    if (!send_recv(sfd, in + soff[to],
                   static_cast<size_t>(sendcounts[to]), rfd,
                   out + roff[from],
                   static_cast<size_t>(recvcounts[from])))
      return -4;
  }
  return 0;
}

// Ring reduce-scatter with per-rank element counts: after p-1 steps
// group rank i holds the full reduction of chunk i (copied to outbuf).
// One ring pass — half the bandwidth of allreduce-then-slice (the
// building block the reference uses inside NCCLHierarchicalAllreduce,
// ops/nccl_operations.cc:188-360; first-class here per SURVEY §2.3's
// FSDP row). buf is scratch and is clobbered.
int hvd_ring_reducescatter(void* h, void* buf, const long long* counts,
                           int dtype, int op, void* outbuf,
                           const int* ranks, int nranks) {
  auto* c = static_cast<RingComm*>(h);
  std::vector<int> group;
  int me = group_index(c, ranks, nranks, &group);
  if (me < 0) return -1;
  int p = static_cast<int>(group.size());
  size_t es = dtype_size(dtype);
  if (es == 0) return -2;
  std::vector<int64_t> off(p + 1, 0);
  for (int i = 0; i < p; ++i) off[i + 1] = off[i] + counts[i];
  char* base = static_cast<char*>(buf);
  if (p == 1) {
    std::memcpy(outbuf, base, static_cast<size_t>(counts[0]) * es);
    return 0;
  }
  int right = c->fds[group[(me + 1) % p]];
  int left = c->fds[group[(me - 1 + p) % p]];
  if (right < 0 || left < 0) return -3;
  int64_t max_chunk = 0;
  for (int i = 0; i < p; ++i)
    max_chunk = std::max(max_chunk, static_cast<int64_t>(counts[i]));
  std::vector<char> tmp(static_cast<size_t>(max_chunk) * es);
  // Chunk (me-s-1) was accumulated in the previous step and moves on;
  // the final receive at s = p-2 lands chunk `me` fully reduced here.
  for (int s = 0; s < p - 1; ++s) {
    int send_c = ((me - s - 1) % p + p) % p;
    int recv_c = ((me - s - 2) % p + p) % p;
    int64_t sn = counts[send_c];
    int64_t rn = counts[recv_c];
    if (!send_recv(right, base + off[send_c] * es,
                   static_cast<size_t>(sn) * es, left, tmp.data(),
                   static_cast<size_t>(rn) * es))
      return -4;
    reduce_buf(base + off[recv_c] * es, tmp.data(), rn, dtype, op);
  }
  std::memcpy(outbuf, base + off[me] * es,
              static_cast<size_t>(counts[me]) * es);
  return 0;
}

int hvd_ring_barrier(void* h, const int* ranks, int nranks) {
  // A 1-element ring allreduce only completes once every group member
  // has entered both ring passes — exactly barrier semantics.
  int64_t z = 0;
  return hvd_ring_allreduce(h, &z, 1, 3, 0, ranks, nranks);
}

void hvd_ring_destroy(void* h) {
  auto* c = static_cast<RingComm*>(h);
  for (int fd : c->fds)
    if (fd >= 0) ::close(fd);
  if (c->listen_fd >= 0) ::close(c->listen_fd);
  delete c;
}

}  // extern "C"
